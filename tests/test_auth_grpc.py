"""Auth enforcement + gRPC service tests.

Reference behaviors: authn/authenticate.go (JWT validation, allowed
networks), authz/authorization.go (group -> index -> level), per-route
gating http_handler.go:497 chkAuthZ; gRPC surface server/grpc.go:160-409
with proto/pilosa.proto message shapes. The authz matrix test is the
VERDICT r3 #5 done-criterion (role x route)."""

import json
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.server import proto
from pilosa_tpu.server.auth import (
    Auth, AuthError, Permissions, issue_token, parse_permissions,
    validate_token,
)
from pilosa_tpu.server.grpc import PilosaServicer, frame, unframe
from pilosa_tpu.server.http import serve

SECRET = "test-secret"
ADMIN_G = "admin-group"
WRITE_G = "writer-group"
READ_G = "reader-group"

PERMS = Permissions(
    user_groups={
        WRITE_G: {"t": "write"},
        READ_G: {"t": "read"},
    },
    admin=ADMIN_G,
)


class TestJWT:
    def test_round_trip(self):
        tok = issue_token(SECRET, [READ_G], subject="alice")
        claims = validate_token(SECRET, tok)
        assert claims["groups"] == [READ_G]
        assert claims["sub"] == "alice"

    def test_bad_signature(self):
        tok = issue_token("other-secret", [READ_G])
        with pytest.raises(AuthError) as e:
            validate_token(SECRET, tok)
        assert e.value.code == 401

    def test_expired(self):
        tok = issue_token(SECRET, [READ_G], ttl_s=-10)
        with pytest.raises(AuthError):
            validate_token(SECRET, tok)

    def test_malformed(self):
        for bad in ("", "a.b", "x.y.z"):
            with pytest.raises(AuthError):
                validate_token(SECRET, bad)


class TestPermissions:
    def test_levels(self):
        assert PERMS.level([ADMIN_G], "t") == 3
        assert PERMS.level([WRITE_G], "t") == 2
        assert PERMS.level([READ_G], "t") == 1
        assert PERMS.level([READ_G], "other") == 0
        assert PERMS.level(["nobody"], "t") == 0

    def test_parse_yaml_subset(self):
        p = parse_permissions(
            'user-groups:\n'
            '  "g1":\n'
            '    "test": "read"\n'
            '    "test2": "write"\n'
            '  "g2":\n'
            '    "test": "admin"\n'
            'admin: "root-group"\n')
        assert p.admin == "root-group"
        assert p.level(["g1"], "test") == 1
        assert p.level(["g1"], "test2") == 2
        assert p.level(["g2"], "test") == 3

    def test_parse_json(self):
        p = parse_permissions(json.dumps(
            {"user-groups": {"g": {"i": "write"}}, "admin": "a"}))
        assert p.level(["g"], "i") == 2
        assert p.admin == "a"


@pytest.fixture(scope="module")
def authed_server():
    api = API()
    api.create_index("t")
    api.create_field("t", "f", {"type": "set"})
    auth = Auth(SECRET, PERMS)  # note: no allowed networks
    srv, _ = serve(api, port=0, background=True, auth=auth)
    host, port = srv.server_address[:2]
    yield f"http://{host}:{port}", api
    srv.shutdown()
    srv.server_close()


def _req(base, method, path, body=b"", token=None, ctype="text/plain"):
    req = urllib.request.Request(base + path, data=body, method=method)
    req.add_header("Content-Type", ctype)
    if token:
        req.add_header("Authorization", "Bearer " + token)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestRouteGating:
    """The authz matrix: role x route (VERDICT r3 #5 done-criterion)."""

    def tok(self, group):
        return issue_token(SECRET, [group])

    @pytest.mark.parametrize("role,group", [
        ("admin", ADMIN_G), ("writer", WRITE_G), ("reader", READ_G)])
    def test_read_query(self, authed_server, role, group):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/index/t/query",
                       b"Count(Row(f=1))", self.tok(group))
        assert code == 200, role

    def test_no_token_rejected(self, authed_server):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/index/t/query", b"Count(Row(f=1))")
        assert code == 401

    @pytest.mark.parametrize("group,want", [
        (ADMIN_G, 200), (WRITE_G, 200), (READ_G, 403)])
    def test_write_query(self, authed_server, group, want):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/index/t/query",
                       b"Set(1, f=1)", self.tok(group))
        assert code == want

    @pytest.mark.parametrize("group,want", [
        (ADMIN_G, 200), (WRITE_G, 403), (READ_G, 403)])
    def test_create_index_needs_admin(self, authed_server, group, want):
        base, _ = authed_server
        code, _ = _req(base, "POST", f"/index/new_{group[:4]}",
                       b"{}", self.tok(group), ctype="application/json")
        assert code == want

    @pytest.mark.parametrize("group,want", [
        # admin clears authz but this single-node API has no peers, so
        # the internal route 404s AFTER the auth check; non-admins are
        # rejected BEFORE reaching it
        (ADMIN_G, 404), (WRITE_G, 403), (READ_G, 403)])
    def test_internal_routes_need_admin(self, authed_server, group, want):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/internal/index/t/query",
                       json.dumps({"query": "Count(Row(f=1))",
                                   "shards": [0]}).encode(),
                       self.tok(group), ctype="application/json")
        assert code == want

    @pytest.mark.parametrize("group,want", [
        (WRITE_G, 200), (READ_G, 403)])
    def test_import_needs_write(self, authed_server, group, want):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/index/t/import",
                       json.dumps({"field": "f", "rows": [1],
                                   "cols": [2]}).encode(),
                       self.tok(group), ctype="application/json")
        assert code == want

    def test_expired_token_rejected(self, authed_server):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/index/t/query", b"Count(Row(f=1))",
                       issue_token(SECRET, [ADMIN_G], ttl_s=-5))
        assert code == 401

    def test_sql_write_gated(self, authed_server):
        base, _ = authed_server
        code, _ = _req(base, "POST", "/sql",
                       b"insert into t (_id, f) values (9, [1])",
                       self.tok(READ_G))
        assert code == 403
        code, _ = _req(base, "POST", "/sql", b"select count(*) from t",
                       self.tok(READ_G))
        assert code == 200


def test_allowed_networks_bypass():
    """Requests from trusted CIDRs skip tokens entirely (reference:
    authn/authenticate.go:426)."""
    api = API()
    api.create_index("t")
    auth = Auth(SECRET, PERMS, allowed_networks=["127.0.0.0/8"])
    srv, _ = serve(api, port=0, background=True, auth=auth)
    try:
        base = f"http://{srv.server_address[0]}:{srv.server_address[1]}"
        code, _ = _req(base, "POST", "/index/t/field/g", b"{}",
                       ctype="application/json")
        assert code == 200  # admin action, no token
    finally:
        srv.shutdown()
        srv.server_close()


class TestGRPC:
    @pytest.fixture()
    def servicer(self):
        api = API()
        return PilosaServicer(api), api

    def test_index_crud_round_trip(self, servicer):
        s, api = servicer
        s.call("CreateIndex", proto._str_field(1, "g1"))
        s.call("CreateIndex", proto._str_field(1, "g2"))
        resp = s.call("GetIndexes", b"")[0]
        names = []
        for f, _, v in proto.iter_fields(resp):
            for f2, _, v2 in proto.iter_fields(v):
                if f2 == 1:
                    names.append(v2.decode())
        assert names == ["g1", "g2"]
        s.call("DeleteIndex", proto._str_field(1, "g1"))
        assert "g1" not in api.holder.indexes

    def test_query_pql_unary(self, servicer):
        s, api = servicer
        api.create_index("t")
        api.create_field("t", "f", {"type": "set"})
        api.query("t", "Set(1, f=7)Set(2, f=7)")
        req = proto._str_field(1, "t") + proto._str_field(2, "Count(Row(f=7))")
        headers, rows = proto.decode_table_response(
            s.call("QueryPQLUnary", req)[0])
        assert rows == [[2]]

    def test_query_sql_unary_and_stream(self, servicer):
        s, api = servicer
        api.sql("create table st (_id id, v int)")
        api.sql("insert into st values (1, 10), (2, 20)")
        req = proto._str_field(1, "select _id, v from st order by v")
        headers, rows = proto.decode_table_response(
            s.call("QuerySQLUnary", req)[0])
        assert [n for n, _ in headers] == ["_id", "v"]
        assert rows == [[1, 10], [2, 20]]
        # streaming: one RowResponse per row, headers on the first
        msgs = s.call("QuerySQL", req)
        assert len(msgs) == 2
        h0, r0 = proto.decode_row_response(msgs[0])
        h1, r1 = proto.decode_row_response(msgs[1])
        assert [n for n, _ in h0] == ["_id", "v"] and r0 == [1, 10]
        assert h1 == [] and r1 == [2, 20]

    def test_http_framed_transport(self):
        """Full gRPC round trip over the HTTP/1.1 framing endpoint."""
        api = API()
        api.sql("create table ht (_id id, n int)")
        api.sql("insert into ht values (1, 5), (2, 9)")
        srv, _ = serve(api, port=0, background=True)
        try:
            base = f"http://{srv.server_address[0]}:{srv.server_address[1]}"
            req = frame(proto._str_field(1, "select sum(n) from ht"))
            r = urllib.request.Request(
                base + "/grpc/pilosa.Pilosa/QuerySQLUnary", data=req,
                method="POST")
            r.add_header("Content-Type", "application/grpc")
            with urllib.request.urlopen(r) as resp:
                assert resp.headers["grpc-status"] == "0"
                msgs = unframe(resp.read())
            _, rows = proto.decode_table_response(msgs[0])
            assert rows == [[14]]
            # unknown method -> UNIMPLEMENTED
            r = urllib.request.Request(
                base + "/grpc/pilosa.Pilosa/Nope", data=frame(b""),
                method="POST")
            with urllib.request.urlopen(r) as resp:
                assert resp.headers["grpc-status"] == "12"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_decimal_and_sets_encode(self, servicer):
        s, api = servicer
        api.sql("create table dt (_id id, d decimal(2), tag idset)")
        api.sql("insert into dt values (1, 12.34, [3, 4])")
        req = proto._str_field(1, "select d, tag from dt")
        _, rows = proto.decode_table_response(
            s.call("QuerySQLUnary", req)[0])
        assert rows[0][0] == pytest.approx(12.34)
        assert rows[0][1] == [3, 4]


class TestGRPCAuthz:
    """Review fix: gRPC methods authorize like their HTTP twins — CRUD
    needs admin, queries escalate on write-ness per index."""

    @pytest.fixture(scope="class")
    def base(self):
        api = API()
        api.create_index("t")
        api.create_field("t", "f", {"type": "set"})
        api.create_index("other")
        srv, _ = serve(api, port=0, background=True,
                       auth=Auth(SECRET, PERMS))
        yield f"http://{srv.server_address[0]}:{srv.server_address[1]}"
        srv.shutdown()
        srv.server_close()

    def _grpc(self, base, method, msg, group):
        req = urllib.request.Request(
            base + f"/grpc/pilosa.Pilosa/{method}", data=frame(msg),
            method="POST")
        req.add_header("Content-Type", "application/grpc")
        req.add_header("Authorization",
                       "Bearer " + issue_token(SECRET, [group]))
        try:
            with urllib.request.urlopen(req) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    def test_writer_cannot_delete_foreign_index(self, base):
        msg = proto._str_field(1, "other")
        assert self._grpc(base, "DeleteIndex", msg, WRITE_G) == 403
        assert self._grpc(base, "DeleteIndex", msg, ADMIN_G) == 200

    def test_writer_cannot_create_index(self, base):
        msg = proto._str_field(1, "newidx")
        assert self._grpc(base, "CreateIndex", msg, WRITE_G) == 403

    def test_reader_read_ok_write_denied(self, base):
        read = (proto._str_field(1, "t") +
                proto._str_field(2, "Count(Row(f=1))"))
        write = (proto._str_field(1, "t") +
                 proto._str_field(2, "Set(9, f=1)"))
        assert self._grpc(base, "QueryPQLUnary", read, READ_G) == 200
        assert self._grpc(base, "QueryPQLUnary", write, READ_G) == 403
        assert self._grpc(base, "QueryPQLUnary", write, WRITE_G) == 200

    def test_sql_ddl_needs_admin(self, base):
        msg = proto._str_field(1, "drop table t")
        assert self._grpc(base, "QuerySQLUnary", msg, WRITE_G) == 403


class TestAuthPrecision:
    """Review fixes: per-table SQL SELECT authz; per-index admin grants
    never confer global admin."""

    @pytest.fixture(scope="class")
    def base(self):
        api = API()
        for name in ("t", "secret"):
            api.create_index(name)
            api.create_field(name, "f", {"type": "set"})
        perms = Permissions(user_groups={
            READ_G: {"t": "read"},
            "idx-admins": {"t": "admin"},
        }, admin=ADMIN_G)
        srv, _ = serve(api, port=0, background=True,
                       auth=Auth(SECRET, perms))
        yield f"http://{srv.server_address[0]}:{srv.server_address[1]}"
        srv.shutdown()
        srv.server_close()

    def test_sql_select_checks_each_table(self, base):
        tok = issue_token(SECRET, [READ_G])
        code, _ = _req(base, "POST", "/sql", b"select count(*) from t", tok)
        assert code == 200
        code, _ = _req(base, "POST", "/sql",
                       b"select count(*) from secret", tok)
        assert code == 403
        code, _ = _req(base, "POST", "/sql",
                       b"select count(*) from t inner join secret "
                       b"on t._id = secret._id", tok)
        assert code == 403

    def test_per_index_admin_not_global(self, base):
        tok = issue_token(SECRET, ["idx-admins"])
        # admin on 't' allows dropping t...
        code, _ = _req(base, "POST", "/sql", b"drop table t", tok)
        assert code == 200
        # ...but NOT dropping (or reading) other tables
        code, _ = _req(base, "POST", "/sql", b"drop table secret", tok)
        assert code == 403
        code, _ = _req(base, "POST", "/sql",
                       b"select count(*) from secret", tok)
        assert code == 403


class TestGRPCInspect:
    def test_inspect_streams_records(self):
        api = API()
        api.sql("create table ins (_id id, seg id, n int)")
        api.sql("insert into ins values (1, 10, 5), (2, 20, 7), (3, 10, 9)")
        s = PilosaServicer(api)
        # columns(field 2) = IdsOrKeys{ids(1)=Uint64Array{vals(1)=[1,3]}}
        ids = proto._len_field(2, proto._len_field(
            1, b"".join(proto._tag(1, 0) + proto._encode_varint(x)
                        for x in (1, 3))))
        req = proto._str_field(1, "ins") + ids
        msgs = s.call("Inspect", req)
        assert len(msgs) == 2
        h0, r0 = proto.decode_row_response(msgs[0])
        assert [n for n, _ in h0] == ["_id", "n", "seg"]
        assert r0 == [1, 5, 10]
        _, r1 = proto.decode_row_response(msgs[1])
        assert r1 == [3, 9, 10]
        # filterFields restricts columns
        req2 = proto._str_field(1, "ins") + ids + proto._str_field(3, "n")
        h, r = proto.decode_row_response(s.call("Inspect", req2)[0])
        assert [n for n, _ in h] == ["_id", "n"] and r == [1, 5]

    def test_inspect_query_filter_packed_ids_and_errors(self):
        api = API()
        api.sql("create table iq (_id id, seg id, n int)")
        api.sql("insert into iq values (1, 10, 5), (2, 20, 7), (3, 10, 9)")
        s = PilosaServicer(api)
        # query filter, no ids
        req = (proto._str_field(1, "iq") +
               proto._str_field(6, "Row(seg=10)"))
        msgs = s.call("Inspect", req)
        assert len(msgs) == 2
        # packed ids (proto3 default from real protoc clients)
        packed = proto._len_field(2, proto._len_field(
            1, proto._len_field(1, bytes([1, 3]))))
        msgs = s.call("Inspect", proto._str_field(1, "iq") + packed)
        assert len(msgs) == 2
        _, r0 = proto.decode_row_response(msgs[0])
        assert r0[0] == 1
        # injection via filterFields is rejected
        bad = (proto._str_field(1, "iq") +
               proto._str_field(3, "n)) Delete(All()"))
        with pytest.raises(KeyError):
            s.call("Inspect", bad)
        assert api.sql("select count(*) from iq").data == [[3]]
        # write query rejected
        with pytest.raises(ValueError):
            s.call("Inspect", proto._str_field(1, "iq") +
                   proto._str_field(6, "Delete(All())"))
        # decimal scale honored in headers
        api.sql("create table dq (_id id, d decimal(2))")
        api.sql("insert into dq values (1, 1.25)")
        h, r = proto.decode_row_response(
            s.call("Inspect", proto._str_field(1, "dq"))[0])
        assert ("d", "DECIMAL(2)") in h and r == [1, 1.25]


class TestOIDC:
    """OIDC login flow against an in-process fake IdP (reference:
    authn/authenticate.go:77-426 + idk/fakeidp; VERDICT r4 missing #4).
    Round-trip: /login redirect -> IdP authorize -> /redirect code
    exchange -> cookies -> authenticated query; plus token refresh and
    the group-claims cache."""

    @pytest.fixture()
    def oidc_server(self):
        from pilosa_tpu.server.oidc import FakeIdP, OAuthConfig, OIDCAuth

        idp = FakeIdP(groups=[{"id": READ_G, "displayName": "readers"}])
        base_idp = idp.serve()
        api = API()
        api.create_index("t")
        api.create_field("t", "f", {"type": "set"})
        cfg = OAuthConfig(
            auth_url=base_idp + "/authorize",
            token_url=base_idp + "/token",
            group_endpoint=base_idp + "/groups",
            logout_endpoint=base_idp + "/logout",
            client_id="cid", client_secret="cs")
        oidc = OIDCAuth(cfg)
        auth = Auth(SECRET, PERMS, oidc=oidc)
        srv, _ = serve(api, port=0, background=True, auth=auth)
        host, port = srv.server_address[:2]
        cfg.redirect_url = f"http://{host}:{port}/redirect"
        yield f"http://{host}:{port}", idp, oidc
        srv.shutdown()
        srv.server_close()
        idp.close()

    def _get(self, url, cookies=None, redirect=False):
        req = urllib.request.Request(url)
        if cookies:
            req.add_header("Cookie", cookies)
        opener = urllib.request.build_opener(_NoRedirect())
        try:
            r = opener.open(req)
            hdrs = r.headers
            return r.status, hdrs, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers, e.read()

    def test_full_login_round_trip(self, oidc_server):
        base, idp, oidc = oidc_server
        # 1. /login redirects to the IdP's authorize endpoint and binds
        #    the anti-CSRF state to this browser via a state cookie
        code_, hdrs, _ = self._get(base + "/login")
        assert code_ == 302 and "/authorize?" in hdrs["Location"]
        login_cookies = hdrs.get_all("Set-Cookie") or []
        state_c = [c for c in login_cookies
                   if c.startswith("molecula-chip-state=")]
        assert state_c, login_cookies
        assert "HttpOnly" in state_c[0] and "SameSite=Lax" in state_c[0]
        state_jar = state_c[0].split(";", 1)[0]
        # 2. IdP authorize redirects back with an auth code
        code_, hdrs, _ = self._get(hdrs["Location"])
        assert code_ == 302 and "code=" in hdrs["Location"]
        # 3. /redirect exchanges the code and sets token cookies (the
        #    state cookie must round-trip or the exchange is refused)
        code_, hdrs, _ = self._get(hdrs["Location"], cookies=state_jar)
        assert code_ == 302
        cookies = hdrs.get_all("Set-Cookie") or []
        pairs = dict(c.split(";", 1)[0].split("=", 1) for c in cookies)
        assert "molecula-chip" in pairs and "refresh-molecula-chip" in pairs
        # the one-shot state cookie is expired on success
        assert any(c.startswith("molecula-chip-state=") and
                   "Expires=Thu, 01 Jan 1970" in c for c in cookies)
        jar = (f"molecula-chip={pairs['molecula-chip']}; "
               f"refresh-molecula-chip={pairs['refresh-molecula-chip']}")
        # 4. a cookie-authenticated request passes authz (READ on t)
        code_, _, body = self._get(base + "/schema", jar)
        assert code_ == 200, body
        # no cookies, no bearer -> 401
        code_, _, _ = self._get(base + "/schema")
        assert code_ == 401

    def test_redirect_without_state_cookie_rejected(self, oidc_server):
        """A /redirect carrying a valid registered state but no bound
        browser cookie is a CSRF (attacker pastes their own callback
        URL into the victim's browser) -> 403."""
        base, idp, oidc = oidc_server
        _, hdrs, _ = self._get(base + "/login")
        _, hdrs, _ = self._get(hdrs["Location"])
        assert "code=" in hdrs["Location"]
        code_, _, _ = self._get(hdrs["Location"])  # no state cookie
        assert code_ == 403
        # wrong state cookie value is equally rejected
        _, hdrs, _ = self._get(base + "/login")
        _, hdrs, _ = self._get(hdrs["Location"])
        code_, _, _ = self._get(hdrs["Location"],
                                cookies="molecula-chip-state=forged")
        assert code_ == 403

    def test_unregistered_state_rejected(self, oidc_server):
        """A state the server never issued fails check_state even when
        the cookie matches (replay across server restarts)."""
        base, idp, oidc = oidc_server
        code_, _, _ = self._get(
            base + "/redirect?code=x&state=neverissued",
            cookies="molecula-chip-state=neverissued")
        assert code_ == 403

    def test_state_cache_evicted(self, oidc_server):
        """Abandoned /login states must not accumulate: _clean_cache
        prunes entries older than the state TTL."""
        base, idp, oidc = oidc_server
        for _ in range(3):
            self._get(base + "/login")
        assert len(oidc._states) >= 3
        for k in list(oidc._states):
            oidc._states[k] -= oidc._state_ttl + 1
        oidc._clean_cache(oidc._clock())
        assert not oidc._states

    def test_secure_cookie_attribute(self, oidc_server):
        """Satellite: Secure is absent by default (plain-HTTP dev) and
        present on every auth cookie when auth.secure-cookies is set."""
        base, idp, oidc = oidc_server
        _, hdrs, _ = self._get(base + "/login")
        assert all("Secure" not in c
                   for c in hdrs.get_all("Set-Cookie") or [])
        from pilosa_tpu.server.http import _state_cookie, _token_cookies
        plain = _token_cookies("a", "r")
        assert all("Secure" not in c for c in plain)
        secured = _token_cookies("a", "r", secure=True)
        assert len(secured) == 2
        assert all(c.endswith("; Secure") for c in secured)
        # expiry variants keep the attribute too (logout over https)
        assert all("Secure" in c
                   for c in _token_cookies("", "", expire=True,
                                           secure=True))
        assert "Secure" in _state_cookie("s1", secure=True)
        assert "Secure" not in _state_cookie("s1")

    def test_group_cache_and_refresh(self, oidc_server):
        base, idp, oidc = oidc_server
        access = idp.mint("bob")
        refresh = "r1"
        idp.refreshes[refresh] = "bob"
        jar = f"molecula-chip={access}; refresh-molecula-chip={refresh}"
        for _ in range(3):
            code_, _, _ = self._get(base + "/schema", jar)
            assert code_ == 200
        assert idp.group_calls == 1  # TTL cache: one IdP groups call
        # expired access token: the server refreshes and rotates cookies
        expired = idp.mint("bob", ttl=-10)
        jar2 = f"molecula-chip={expired}; refresh-molecula-chip={refresh}"
        code_, hdrs, _ = self._get(base + "/schema", jar2)
        assert code_ == 200
        assert any(c.startswith("molecula-chip=")
                   for c in hdrs.get_all("Set-Cookie") or [])
        # garbage access token -> 401, not a 500
        code_, _, _ = self._get(base + "/schema",
                                "molecula-chip=notajwt")
        assert code_ == 401

    def test_logout_clears_session(self, oidc_server):
        base, idp, oidc = oidc_server
        access = idp.mint("eve")
        jar = f"molecula-chip={access}"
        assert self._get(base + "/schema", jar)[0] == 200
        code_, hdrs, _ = self._get(base + "/logout", jar)
        assert code_ == 302
        assert any("Expires=Thu, 01 Jan 1970" in c
                   for c in hdrs.get_all("Set-Cookie") or [])
        assert access not in oidc._groups_cache


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


class TestOIDCInfoRoutes:
    def test_userinfo_and_oauth_config(self):
        from pilosa_tpu.server.oidc import FakeIdP, OAuthConfig, OIDCAuth

        idp = FakeIdP(groups=[{"id": READ_G, "displayName": "readers"}])
        base_idp = idp.serve()
        api = API()
        cfg = OAuthConfig(auth_url=base_idp + "/authorize",
                          token_url=base_idp + "/token",
                          group_endpoint=base_idp + "/groups",
                          client_id="cid", client_secret="SECRETVALUE")
        auth = Auth(SECRET, PERMS, oidc=OIDCAuth(cfg))
        srv, _ = serve(api, port=0, background=True, auth=auth)
        host, port = srv.server_address[:2]
        base = f"http://{host}:{port}"
        try:
            access = idp.mint("carol")
            req = urllib.request.Request(base + "/userinfo")
            req.add_header("Cookie", f"molecula-chip={access}")
            with urllib.request.urlopen(req) as r:
                info = json.loads(r.read())
            assert info["userid"] == "carol"
            assert info["groups"] == [{"id": READ_G}]
            # oauth-config needs admin (unlisted internal route) and must
            # not leak the client secret
            tok = issue_token(SECRET, [ADMIN_G], subject="admin")
            req = urllib.request.Request(base + "/internal/oauth-config")
            req.add_header("Authorization", "Bearer " + tok)
            with urllib.request.urlopen(req) as r:
                conf = json.loads(r.read())
            assert conf["clientId"] == "cid"
            assert "SECRETVALUE" not in json.dumps(conf)
            # no cookies -> 401 from userinfo
            try:
                urllib.request.urlopen(base + "/userinfo")
                raise AssertionError("expected 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
        finally:
            srv.shutdown()
            srv.server_close()
            idp.close()


class TestSQLAuthzTail:
    """Round-5 review findings: FROM-subqueries and COPY must not bypass
    per-table grants."""

    @pytest.fixture(scope="class")
    def server(self):
        api = API()
        for t in ("pub", "secret"):
            api.create_index(t)
            api.holder.index(t).create_field(
                "v", __import__("pilosa_tpu.core.schema",
                                fromlist=["FieldOptions", "FieldType"]
                                ).FieldOptions(
                    type=__import__("pilosa_tpu.core.schema",
                                    fromlist=["FieldType"]).FieldType.INT))
        api.sql("insert into pub (_id, v) values (1, 1)")
        api.sql("insert into secret (_id, v) values (1, 99)")
        perms = Permissions(user_groups={
            READ_G: {"pub": "read"},
            WRITE_G: {"pub": "write"},
        }, admin=ADMIN_G)
        srv, _ = serve(api, port=0, background=True,
                       auth=Auth(SECRET, perms))
        host, port = srv.server_address[:2]
        yield f"http://{host}:{port}"
        srv.shutdown()
        srv.server_close()

    def _sql(self, base, text, groups):
        tok = issue_token(SECRET, groups, subject="u")
        req = urllib.request.Request(base + "/sql", data=text.encode(),
                                     method="POST")
        req.add_header("Content-Type", "text/plain")
        req.add_header("Authorization", "Bearer " + tok)
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_derived_table_needs_source_read(self, server):
        code, _ = self._sql(server, "select v from pub", [READ_G])
        assert code == 200
        code, _ = self._sql(server, "select v from secret", [READ_G])
        assert code == 403
        # the bypass: wrapping in a FROM-subquery must NOT help
        code, _ = self._sql(
            server, "select v from (select v from secret) x", [READ_G])
        assert code == 403
        code, body = self._sql(
            server, "select v from (select v from pub) x", [READ_G])
        assert code == 200 and body["data"] == [[1]]

    def test_copy_needs_read_and_admin(self, server):
        # write grant on pub alone: cannot read secret via COPY
        code, _ = self._sql(server, "copy secret to leak", [WRITE_G])
        assert code == 403
        # read on source but no admin: still refused (implicit CREATE)
        code, _ = self._sql(server, "copy pub to pub2", [READ_G])
        assert code == 403
        # external URL needs admin even with read on source
        code, _ = self._sql(
            server, "copy pub to x with url 'http://127.0.0.1:1'",
            [READ_G, WRITE_G])
        assert code == 403
        # admin may copy
        code, _ = self._sql(server, "copy pub to pub2", [ADMIN_G])
        assert code == 200
