"""Client library + extended IDK sources.

Reference: client/orm.go serialization semantics, client/client.go +
importer.go round trips, idk/sql/, idk/kinesis/, Avro registry decoding.
The client round-trip test is the VERDICT r3 #9 done-criterion."""

import json
import sqlite3
import struct

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.client import Client, Schema
from pilosa_tpu.core.schema import FieldOptions, FieldType
from pilosa_tpu.ingest.ingest import Ingester
from pilosa_tpu.ingest.sources_ext import (
    AvroSource, KinesisSource, SQLSource, avro_decode,
)
from pilosa_tpu.server.http import serve
from pilosa_tpu.shardwidth import SHARD_WIDTH


class TestORM:
    def test_serialization(self):
        s = Schema()
        idx = s.index("i")
        f = idx.field("f")
        g = idx.field("g")
        assert f.row(5).serialize() == "Row(f=5)"
        assert f.row("k").serialize() == "Row(f='k')"
        assert (f.row(1) & g.row(2)).serialize() == \
            "Intersect(Row(f=1), Row(g=2))"
        assert (f.row(1) | g.row(2)).serialize() == \
            "Union(Row(f=1), Row(g=2))"
        assert (f.row(1) - g.row(2)).serialize() == \
            "Difference(Row(f=1), Row(g=2))"
        assert (~f.row(1)).serialize() == "Not(Row(f=1))"
        assert idx.count(f.row(1)).serialize() == "Count(Row(f=1))"
        assert f.topn(5).serialize() == "TopN(f, n=5)"
        n = idx.field("n")
        assert n.gt(3).serialize() == "Row(n > 3)"
        assert n.between(2, 8).serialize() == "Row(2 <= n <= 8)"
        assert n.sum(f.row(1)).serialize() == "Sum(Row(f=1), field=n)"
        assert f.set(3, 10).serialize() == "Set(10, f=3)"
        assert idx.group_by(f.rows(), limit=4).serialize() == \
            "GroupBy(Rows(f), limit=4)"
        assert idx.batch_query(f.set(1, 2), idx.count(f.row(1))
                               ).serialize() == "Set(2, f=1)Count(Row(f=1))"


@pytest.fixture()
def served():
    api = API()
    srv, _ = serve(api, port=0, background=True)
    yield f"http://{srv.server_address[0]}:{srv.server_address[1]}", api
    srv.shutdown()
    srv.server_close()


class TestClientRoundTrip:
    def test_schema_sync_import_query(self, served):
        base, api = served
        c = Client(base)
        schema = Schema()
        idx = schema.index("ci")
        f = idx.field("f", type="set")
        n = idx.field("n", type="int")
        c.sync_schema(schema)
        assert "ci" in api.holder.indexes
        # shard-aware roaring import across two shards
        bits = [(1, 5), (1, SHARD_WIDTH + 9), (2, 7)]
        c.import_bits("ci", "f", bits)
        assert c.query(idx.count(f.row(1))) == [2]
        assert c.query(f.row(2))[0]["columns"] == [7]
        r = c.query(f.row(1))
        assert r[0]["columns"] == [5, SHARD_WIDTH + 9]
        # BSI values + ORM aggregate
        c.import_values("ci", "n", [(5, 10), (7, -3)])
        assert c.query(n.sum())[0]["value"] == 7
        # ORM write + sql
        c.query(f.set(9, 11))
        assert c.query(idx.count(f.row(9))) == [1]
        out = c.sql("select count(*) from ci")
        assert out["data"] == [[4]]
        # schema() reads back what we created
        got = c.schema()
        assert {i.name for i in got.indexes()} >= {"ci"}

    def test_json_import_path_and_keyed(self, served):
        base, api = served
        c = Client(base)
        c.create_index("kj", keys=True)
        c._json("POST", "/index/kj/field/tag",
                {"options": {"type": "set", "keys": True}})
        c.import_keyed_bits("kj", "tag", [("red", "a"), ("red", "b"),
                                          ("blue", "a")])
        out = c.query("Count(Row(tag='red'))", index="kj")
        assert out == [2]
        # non-roaring JSON path
        c.create_index("pj")
        c._json("POST", "/index/pj/field/f", {"options": {"type": "set"}})
        c.import_bits("pj", "f", [(1, 2), (1, 3)], roaring=False)
        assert c.query("Count(Row(f=1))", index="pj") == [2]


class TestSQLSource:
    def test_sqlite_ingest(self):
        conn = sqlite3.connect(":memory:")
        conn.execute("create table people "
                     "(id integer, city text, age integer)")
        conn.executemany("insert into people values (?, ?, ?)",
                         [(1, "paris", 30), (2, "tokyo", 41),
                          (3, "paris", 25)])
        api = API()
        src = SQLSource(conn, "select id, city, age from people",
                        types={"age": "int"})
        n = Ingester(api, "people", src).run()
        assert n == 3
        assert api.query("people", "Count(Row(city='paris'))")[0] == 2
        assert api.query("people", "Sum(field=age)")[0].val == 96


class _StubKinesis:
    """boto3-shaped stub (reference tests use localstack; we inject)."""

    def __init__(self, records):
        self._records = [json.dumps(r).encode() for r in records]

    def describe_stream(self, StreamName):
        return {"StreamDescription": {"Shards": [{"ShardId": "s-0"}]}}

    def get_shard_iterator(self, **kw):
        return {"ShardIterator": "it-0"}

    def get_records(self, ShardIterator):
        recs, self._records = self._records, []
        return {"Records": [{"Data": d} for d in recs],
                "NextShardIterator": None}


class TestKinesisSource:
    def test_stub_stream_ingest(self):
        src = KinesisSource(
            "events", client=_StubKinesis([
                {"id": 1, "kind": "click"},
                {"id": 2, "kind": "view"},
                {"id": 3, "kind": "click"},
            ]),
            schema=[("kind", FieldOptions(type=FieldType.MUTEX, keys=True))])
        api = API()
        assert Ingester(api, "ev", src).run() == 3
        assert api.query("ev", "Count(Row(kind='click'))")[0] == 2

    def test_missing_boto3_is_loud(self):
        with pytest.raises(RuntimeError):
            KinesisSource("events")


def _avro_long(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_string(s: str) -> bytes:
    raw = s.encode()
    return _avro_long(len(raw)) + raw


AVRO_SCHEMA = {
    "type": "record", "name": "ev",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "city", "type": "string"},
        {"name": "score", "type": "double"},
        {"name": "maybe", "type": ["null", "long"]},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
    ],
}


def _avro_record(rid, city, score, maybe, tags):
    out = _avro_long(rid) + _avro_string(city) + struct.pack("<d", score)
    if maybe is None:
        out += _avro_long(0)
    else:
        out += _avro_long(1) + _avro_long(maybe)
    if tags:
        out += _avro_long(len(tags))
        for t in tags:
            out += _avro_string(t)
    out += _avro_long(0)
    return b"\x00" + (7).to_bytes(4, "big") + out


class TestAvroSource:
    def test_decode(self):
        payload = _avro_record(5, "oslo", 1.5, 9, ["a", "b"])
        rec = avro_decode(AVRO_SCHEMA, payload[5:])
        assert rec == {"id": 5, "city": "oslo", "score": 1.5,
                       "maybe": 9, "tags": ["a", "b"]}

    def test_registry_ingest(self):
        payloads = [
            _avro_record(1, "oslo", 2.5, None, ["x"]),
            _avro_record(2, "kyiv", 0.5, 4, ["x", "y"]),
        ]
        src = AvroSource(payloads, registry={7: AVRO_SCHEMA})
        schema_fields = dict((n, o.type) for n, o in src.schema())
        assert schema_fields["tags"] == FieldType.SET
        api = API()
        assert Ingester(api, "av", src).run() == 2
        assert api.query("av", "Count(Row(city='oslo'))")[0] == 1
        assert api.query("av", "Count(Row(tags='x'))")[0] == 2
