"""Cluster metadata gossip tests: version-vector state tables, delta
windows, seeded anti-entropy rounds, piggybacked envelopes, exact
remote-leg cache invalidation (zero TTL reliance), op-scoped fault
injection, and breaker-state sharing.

scripts/tier1.sh re-runs this file under two fixed values of
PILOSA_TPU_GOSSIP_SEED — every test must hold for ANY seed: the seed
only steers which peer an anti-entropy round contacts, and tests that
assert exact peer sequences construct their agents with explicit
seeds."""

import json
import types
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    CircuitBreaker, FaultPlan, GossipAgent, GossipState, InjectedFault,
    LocalCluster, NodeDownError,
)
from pilosa_tpu.cluster.resilience import (
    BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN,
)
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.config import Config
from pilosa_tpu.gossip import _reset_ttl_warning
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.sched import ManualClock
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _state(node_id="A", **kw):
    kw.setdefault("clock", ManualClock())
    kw.setdefault("registry", MetricsRegistry())
    return GossipState(node_id, **kw)


class TestGossipState:
    def test_bump_assigns_monotone_seqs_and_dedups_unchanged(self):
        st = _state()
        assert st.bump_local(("f", "i", "f1", 0), [1, 5]) is True
        assert st.bump_local(("f", "i", "f1", 0), [1, 5]) is False  # same
        assert st.bump_local(("f", "i", "f1", 0), [1, 6]) is True
        assert st.digest() == {"A": 2}
        assert len(st) == 1  # re-bump replaces, never accumulates

    def test_deltas_since_windows_and_digest(self):
        st = _state()
        for i in range(4):
            st.bump_local(("f", "i", "f1", i), [1, 1])
        assert [d["s"] for d in st.deltas_since({})] == [1, 2, 3, 4]
        assert [d["s"] for d in st.deltas_since({"A": 2})] == [3, 4]
        assert st.deltas_since({"A": 4}) == []

    def test_cap_truncation_keeps_lowest_seqs(self):
        # complete-window invariant: a truncated batch must be the LOW
        # end of the window, so the receiver's digest never advances
        # past an entry it missed
        st = _state()
        for i in range(10):
            st.bump_local(("f", "i", "f1", i), [1, 1])
        got = st.deltas_since({}, cap=3)
        assert [d["s"] for d in got] == [1, 2, 3]

    def test_apply_is_idempotent_and_newest_wins(self):
        a, b = _state("A"), _state("B")
        a.bump_local(("h", "A"), "up")
        deltas = a.deltas_since({})
        assert b.apply(deltas) == 1
        assert b.apply(deltas) == 0  # replay: no-op
        a.bump_local(("h", "A"), "down")
        newer = a.deltas_since({})
        assert b.apply(newer + deltas) == 1  # stale entry loses
        (ent,) = b.entries_json()["A"].values()
        assert ent["v"] == "down"

    def test_apply_skips_own_origin(self):
        a = _state("A")
        echoed = [{"o": "A", "k": ["h", "A"], "v": "up", "s": 9, "t": 0.0}]
        assert a.apply(echoed) == 0
        assert a.digest() == {}

    def test_remote_fingerprint_filters_and_tracks_seqs(self):
        a, b = _state("A"), _state("B")
        a.bump_local(("f", "i", "f1", 0), [1, 5])
        a.bump_local(("f", "i", "f1", 3), [1, 5])  # shard outside set
        a.bump_local(("f", "other", "f1", 0), [1, 5])  # other index
        b.apply(a.deltas_since({}))
        b.bump_local(("f", "i", "f1", 0), [1, 7])
        fp = b.remote_fingerprint("i", [0, 1])
        assert fp == (("A", "f1", 0, 1), ("B", "f1", 0, 1))
        before = fp
        b.apply([{"o": "A", "k": ["f", "i", "f1", 0], "v": [1, 9],
                  "s": 4, "t": 0.0}])
        assert b.remote_fingerprint("i", [0, 1]) != before

    def test_refresh_index_tracks_real_writes(self):
        api = API()
        api.create_index("ri")
        api.create_field("ri", "f")
        st = _state("A")
        idx = api.holder.indexes["ri"]
        assert st.refresh_index(idx) == 0  # no fragments yet
        api.import_bits("ri", "f", rows=[1], cols=[5])
        assert st.refresh_index(idx) >= 1
        fp1 = st.remote_fingerprint("ri", [0])
        assert st.refresh_index(idx) == 0  # no change, no bump
        api.import_bits("ri", "f", rows=[1], cols=[6])
        assert st.refresh_index(idx) >= 1
        assert st.remote_fingerprint("ri", [0]) != fp1


def _mknodes(n):
    return [Node(id=f"node{i}", uri="") for i in range(n)]


class _LoopNet:
    """In-process transport: routes gossip exchanges straight between
    agents (no HTTP), recording the exchange trace."""

    def __init__(self):
        self.agents = {}
        self.trace = []

    def gossip_exchange(self, node, payload):
        env = payload["gossip"]
        self.trace.append((env["from"], node.id))
        peer = self.agents[node.id]
        peer.receive(env)
        return {"gossip": peer.envelope(env["from"])}


def _mkagents(n, seed=11, clock=None, net=None):
    net = net or _LoopNet()
    clock = clock or ManualClock()
    nodes = _mknodes(n)
    agents = []
    for node in nodes:
        holder = types.SimpleNamespace(indexes={})
        ag = GossipAgent(
            node.id, net, lambda nid=node.id: [x for x in nodes
                                               if x.id != nid],
            holder, seed=seed, clock=clock, registry=MetricsRegistry())
        net.agents[node.id] = ag
        agents.append(ag)
    return agents, net


class TestGossipAgent:
    def test_roundtrip_then_silent(self):
        agents, net = _mkagents(2)
        a, b = agents
        a.state.bump_local(("h", "node0"), "up")
        assert a.run_round() == 0  # pushes; B had nothing for us
        assert b.state.digest() == {"node0": 1}
        # B now holds and advertises node0@1; next round ships nothing
        env = a.envelope("node1")
        assert env["deltas"] == []

    def test_transitive_relay(self):
        # A -> B -> C without A ever talking to C
        agents, net = _mkagents(3)
        a, b, c = agents
        a.state.bump_local(("h", "node0"), "up")
        net.trace.clear()
        b.receive(a.envelope(None))
        c.receive(b.envelope(None))
        assert c.state.digest().get("node0") == 1

    def test_seeded_peer_choice_is_deterministic(self):
        traces = []
        for _ in range(2):
            agents, net = _mkagents(4, seed=5)
            for _ in range(6):
                for ag in agents:
                    ag.run_round()
            traces.append(list(net.trace))
        assert traces[0] == traces[1]
        # a different seed picks a different exchange sequence
        agents, net2 = _mkagents(4, seed=6)
        for _ in range(6):
            for ag in agents:
                ag.run_round()
        assert net2.trace != traces[0]

    def test_rounds_deterministic_under_manual_clock(self):
        # full determinism: same seed + ManualClock => byte-identical
        # final state tables (stamps included)
        finals = []
        for _ in range(2):
            agents, _ = _mkagents(3, seed=9, clock=ManualClock())
            agents[0].state.bump_local(("f", "i", "f1", 0), [1, 2])
            agents[1].state.bump_local(("f", "i", "f1", 1), [1, 4])
            for ag in agents:
                ag.run_round()
            finals.append([json.dumps(ag.state_json(), sort_keys=True)
                           for ag in agents])
        assert finals[0] == finals[1]

    def test_env_seed_default(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_GOSSIP_SEED", "42")
        ag = GossipAgent("x", None, lambda: [],
                         types.SimpleNamespace(indexes={}),
                         registry=MetricsRegistry())
        assert ag.seed == 42

    def test_idle_round_without_peers(self):
        reg = MetricsRegistry()
        ag = GossipAgent("x", None, lambda: [],
                         types.SimpleNamespace(indexes={}),
                         clock=ManualClock(), registry=reg)
        assert ag.run_round() == 0
        assert reg.value(M.METRIC_GOSSIP_ROUNDS, outcome="idle") == 1.0

    def test_round_survives_down_peer(self):
        class _DeadNet:
            def gossip_exchange(self, node, payload):
                raise NodeDownError("down")

        reg = MetricsRegistry()
        ag = GossipAgent("x", _DeadNet(), lambda: _mknodes(2)[1:],
                         types.SimpleNamespace(indexes={}),
                         clock=ManualClock(), registry=reg)
        assert ag.run_round() == 0
        assert reg.value(M.METRIC_GOSSIP_ROUNDS, outcome="err") == 1.0

    def test_from_config_maps_fields(self):
        cfg = Config(gossip_interval_ms=7.0, gossip_fanout=2,
                     gossip_seed=13, gossip_max_deltas=99,
                     gossip_piggyback=False)
        ag = GossipAgent.from_config(
            "x", None, lambda: [], types.SimpleNamespace(indexes={}),
            cfg, registry=MetricsRegistry())
        assert (ag.interval_ms, ag.fanout, ag.seed, ag.max_deltas,
                ag.piggyback) == (7.0, 2, 13, 99, False)


class TestFaultPlanOps:
    def test_op_scoped_rule_only_matches_its_op(self):
        plan = FaultPlan(seed=1)
        plan.drop("n1", op="gossip")
        with pytest.raises(InjectedFault):
            plan.on_request("n1", op="gossip")
        plan.on_request("n1", op="query")  # unscoped op passes
        plan.on_request("n1")  # untagged request passes

    def test_unscoped_rule_still_matches_everything(self):
        # backward compatibility: pre-op rules and positional calls
        plan = FaultPlan(seed=1)
        plan.drop("n1")
        with pytest.raises(InjectedFault):
            plan.on_request("n1")
        with pytest.raises(InjectedFault):
            plan.on_request("n1", op="gossip")

    def test_op_scoping_at_the_client_boundary(self):
        # drop gossip exchanges only: queries keep working while the
        # anti-entropy channel is down
        plan = FaultPlan(seed=1).drop("node1", op="gossip")
        c = LocalCluster(2, fault_plan=plan)
        try:
            co = c.coordinator
            co.create_index("fo")
            co.create_field("fo", "f")
            cols = list(range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 2))
            co.import_bits("fo", "f", rows=[1] * len(cols), cols=cols)
            c.enable_gossip(registry=MetricsRegistry())
            n = co.query("fo", "Count(Row(f=1))")[0]
            assert n == len(cols)
            ag = co.gossip
            peer = c[1].node
            with pytest.raises(NodeDownError):
                ag.client.gossip_exchange(peer, {"gossip": ag.envelope(None)})
        finally:
            c.close()


class TestBreakerSharing:
    def _mk(self):
        clk = ManualClock()
        events = []
        br = CircuitBreaker(threshold=3, open_s=2.0, clock=clk,
                            registry=MetricsRegistry())
        br.add_listener(lambda nid, frm, to: events.append((nid, frm, to)))
        return br, clk, events

    def test_apply_remote_open_prewarm_and_countdown(self):
        br, clk, events = self._mk()
        assert br.apply_remote("n2", BREAKER_OPEN) is True
        assert br.state("n2") == BREAKER_OPEN
        assert events == []  # remote applies never notify listeners
        assert br.allow("n2") is False
        clk.advance(2.5)  # OUR open_s countdown gates OUR probe
        assert br.allow("n2") is True
        assert br.state("n2") == BREAKER_HALF_OPEN

    def test_half_open_gossip_adopted_as_open(self):
        br, clk, _ = self._mk()
        assert br.apply_remote("n2", BREAKER_HALF_OPEN) is True
        assert br.state("n2") == BREAKER_OPEN

    def test_remote_close_only_reverts_remote_state(self):
        br, clk, _ = self._mk()
        # locally earned open: a peer's recovery claim must not close it
        for _ in range(3):
            br.record_failure("n2")
        assert br.state("n2") == BREAKER_OPEN
        assert br.apply_remote("n2", BREAKER_CLOSED) is False
        assert br.state("n2") == BREAKER_OPEN
        # remote-warmed open: the same peer's close reverts it
        br.apply_remote("n3", BREAKER_OPEN)
        assert br.apply_remote("n3", BREAKER_CLOSED) is True
        assert br.state("n3") == BREAKER_CLOSED

    def test_local_evidence_overrides_remote_warm(self):
        br, clk, _ = self._mk()
        br.apply_remote("n2", BREAKER_OPEN)
        clk.advance(2.5)
        assert br.allow("n2")  # half-open probe
        br.record_success("n2")  # our own probe succeeded
        assert br.state("n2") == BREAKER_CLOSED
        # now a stale remote close is a no-op (slot is locally owned)
        assert br.apply_remote("n2", BREAKER_CLOSED) is False

    def test_local_transitions_notify_listeners(self):
        br, clk, events = self._mk()
        for _ in range(3):
            br.record_failure("n2")
        assert events == [("n2", BREAKER_CLOSED, BREAKER_OPEN)]

    def test_remote_open_when_already_open_is_noop(self):
        br, clk, _ = self._mk()
        br.apply_remote("n2", BREAKER_OPEN)
        t0 = clk.now()
        clk.advance(1.0)
        assert br.apply_remote("n2", BREAKER_OPEN) is False  # keep countdown


def _fill(cluster, index, n_shards=4, row=3):
    co = cluster.coordinator
    co.create_index(index)
    co.create_field(index, "f")
    cols = list(range(0, n_shards * SHARD_WIDTH, SHARD_WIDTH // 4))
    co.import_bits(index, "f", rows=[row] * len(cols), cols=cols)
    return len(cols)


def _owner_with_shards(cluster, index):
    for node in cluster.nodes[1:]:
        idx = node.api.holder.indexes.get(index)
        if idx is not None and idx.shards():
            return node
    pytest.skip("placement put no shards on a non-coordinator")


class TestClusterGossip:
    def test_convergence_to_identical_state(self):
        c = LocalCluster(3)
        try:
            _fill(c, "cv")
            c.enable_gossip(registry=MetricsRegistry())
            # fanout=1: N-1 sequential full sweeps bound convergence
            c.run_gossip_rounds(len(c) + 1)
            digests = [n.gossip.state.digest() for n in c.nodes]
            assert digests[0] == digests[1] == digests[2]
            tables = [json.dumps(
                {o: {k: {kk: vv for kk, vv in e.items() if kk != "t"}
                     for k, e in tab.items()}
                 for o, tab in n.gossip.state.entries_json().items()},
                sort_keys=True) for n in c.nodes]
            assert tables[0] == tables[1] == tables[2]
        finally:
            c.close()

    def test_convergence_under_drops_delays_flaps(self):
        plan = (FaultPlan(seed=2)
                .drop("node1", count=6, op="gossip")
                .delay("node2", 0.005, count=4, op="gossip")
                .flap("node0", period=3, op="gossip"))
        c = LocalCluster(3, fault_plan=plan)
        try:
            _fill(c, "cf")
            c.enable_gossip(registry=MetricsRegistry())
            # drops cost whole exchanges; give the sweep extra rounds
            c.run_gossip_rounds(3 * len(c))
            digests = [n.gossip.state.digest() for n in c.nodes]
            assert digests[0] == digests[1] == digests[2]
        finally:
            c.close()

    def test_exact_invalidation_zero_ttl(self):
        # the acceptance scenario: write on node B (never through the
        # coordinator), coordinator's cached remote leg invalidates
        # after convergence, with the TTL knob at 0 the whole time
        c = LocalCluster(2)
        try:
            n = _fill(c, "xi")
            c.enable_gossip(registry=MetricsRegistry())
            c.run_gossip_rounds(3)
            co = c.coordinator
            cache = co.enable_cache(ttl_ms=0, registry=MetricsRegistry())
            assert cache.ttl_ms == 0
            assert co.query("xi", "Count(Row(f=3))")[0] == n
            assert co.query("xi", "Count(Row(f=3))")[0] == n
            assert any(k[0] == "rlegg" for k in cache._entries)
            assert not any(k[0] == "rleg" for k in cache._entries)
            hits = cache.stats()["hits"]
            assert hits >= 1  # remote leg served from cache
            owner = _owner_with_shards(c, "xi")
            shard = sorted(owner.api.holder.indexes["xi"].shards())[0]
            owner.api.import_bits("xi", "f", rows=[3],
                                  cols=[shard * SHARD_WIDTH + 999])
            owner._announce_shards("xi")
            c.run_gossip_rounds(3)
            assert co.query("xi", "Count(Row(f=3))")[0] == n + 1
        finally:
            c.close()

    def test_write_through_invalidates_immediately(self):
        # a coordinator-forwarded write's response envelope carries the
        # owner's new versions, so the next read is fresh with ZERO
        # anti-entropy rounds
        c = LocalCluster(2)
        try:
            n = _fill(c, "wt")
            c.enable_gossip(registry=MetricsRegistry())
            c.run_gossip_rounds(3)
            co = c.coordinator
            co.enable_cache(ttl_ms=0, registry=MetricsRegistry())
            assert co.query("wt", "Count(Row(f=3))")[0] == n
            owner = _owner_with_shards(c, "wt")
            shard = sorted(owner.api.holder.indexes["wt"].shards())[0]
            co.import_bits("wt", "f", rows=[3],
                           cols=[shard * SHARD_WIDTH + 999])
            # no run_gossip_rounds on purpose
            assert co.query("wt", "Count(Row(f=3))")[0] == n + 1
        finally:
            c.close()

    def test_piggyback_spreads_without_rounds(self):
        c = LocalCluster(2)
        try:
            _fill(c, "pb")
            c.enable_gossip(registry=MetricsRegistry())
            co = c.coordinator
            # a single fan-out query piggybacks envelopes both ways
            co.query("pb", "Count(Row(f=3))")
            other = c[1]
            assert co.node.id in other.gossip.state.digest() or \
                other.node.id in co.gossip.state.digest()
        finally:
            c.close()

    def test_breaker_prewarm_across_cluster(self):
        # node1 ends up open for a target it never failed against
        c = LocalCluster(2)
        try:
            _fill(c, "bp")
            c.enable_gossip(registry=MetricsRegistry())
            reg0, reg1 = MetricsRegistry(), MetricsRegistry()
            res0 = c[0].enable_resilience(registry=reg0)
            res1 = c[1].enable_resilience(registry=reg1)
            for _ in range(3):
                res0.breaker.record_failure("nodeX")
            assert res0.breaker.state("nodeX") == BREAKER_OPEN
            c.run_gossip_rounds(3)
            assert res1.breaker.state("nodeX") == BREAKER_OPEN
            assert reg1.value(M.METRIC_GOSSIP_BREAKER_PREWARMS,
                              node="nodeX") >= 1.0
        finally:
            c.close()

    def test_prewarm_never_applies_to_self(self):
        c = LocalCluster(2)
        try:
            _fill(c, "ps")
            c.enable_gossip(registry=MetricsRegistry())
            res0 = c[0].enable_resilience(registry=MetricsRegistry())
            res1 = c[1].enable_resilience(registry=MetricsRegistry())
            # node0 thinks node1 is down; node1 must not open a breaker
            # for ITSELF off that gossip
            for _ in range(3):
                res0.breaker.record_failure("node1")
            c.run_gossip_rounds(3)
            assert res1.breaker.state("node1") == BREAKER_CLOSED
        finally:
            c.close()

    def test_ttl_deprecation_warns_once(self):
        _reset_ttl_warning()
        c = LocalCluster(2)
        try:
            c.enable_gossip(registry=MetricsRegistry())
            with pytest.warns(DeprecationWarning, match="ttl-ms"):
                c[0].enable_cache(ttl_ms=500, registry=MetricsRegistry())
            # second enable: warning already spent
            import warnings as _w

            with _w.catch_warnings():
                _w.simplefilter("error")
                c[1].enable_cache(ttl_ms=500, registry=MetricsRegistry())
        finally:
            _reset_ttl_warning()
            c.close()

    def test_state_endpoint_over_http(self):
        c = LocalCluster(2)
        try:
            _fill(c, "se")
            c.enable_gossip(registry=MetricsRegistry())
            c.run_gossip_rounds(2)
            uri = c[0].node.uri + "/internal/gossip/state"
            with urllib.request.urlopen(uri, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["enabled"] is True
            assert out["node"] == "node0"
            assert "node0" in out["entries"]
            assert out["digest"]
        finally:
            c.close()

    def test_state_endpoint_reports_disabled(self):
        c = LocalCluster(1)
        try:
            uri = c[0].node.uri + "/internal/gossip/state"
            with urllib.request.urlopen(uri, timeout=10) as resp:
                assert json.loads(resp.read()) == {"enabled": False}
        finally:
            c.close()

    def test_exchange_endpoint_round_trips(self):
        c = LocalCluster(2)
        try:
            _fill(c, "xe")
            c.enable_gossip(registry=MetricsRegistry())
            ag0 = c[0].gossip
            req = urllib.request.Request(
                c[1].node.uri + "/internal/gossip/exchange",
                data=json.dumps({"gossip": ag0.envelope(None)}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["enabled"] is True
            assert out["gossip"]["from"] == "node1"
            # the peer now holds our digest
            assert "node0" in c[1].gossip.state.digest()
        finally:
            c.close()

    def test_disable_gossip_detaches_everything(self):
        c = LocalCluster(2)
        try:
            _fill(c, "dg")
            c.enable_gossip(registry=MetricsRegistry())
            co = c.coordinator
            assert co.gossip is not None
            assert co.client.gossip is not None
            co.disable_gossip()
            assert co.gossip is None
            assert co.client.gossip is None
            # gossip-off keeps the pre-gossip cache behavior intact
            cache = co.enable_cache(ttl_ms=0, registry=MetricsRegistry())
            co.query("dg", "Count(Row(f=3))")
            assert not any(k[0] in ("rleg", "rlegg")
                           for k in cache._entries)
        finally:
            c.close()


class TestGossipMetrics:
    def test_exposition_contains_gossip_series(self):
        agents, _ = _mkagents(2, seed=3)
        a, b = agents
        a.state.bump_local(("h", "node0"), "up")
        a.run_round()
        b.run_round()
        text = a.registry.prometheus_text()
        for name in (M.METRIC_GOSSIP_ROUNDS, M.METRIC_GOSSIP_DELTAS_SENT,
                     M.METRIC_GOSSIP_ENTRIES, M.METRIC_GOSSIP_ROUND_MS):
            assert name in text, name

    def test_staleness_histogram_observes_applies(self):
        clk = ManualClock()
        reg = MetricsRegistry()
        a = GossipState("A", clock=clk, registry=reg)
        b = GossipState("B", clock=clk, registry=reg)
        a.bump_local(("h", "A"), "up")
        deltas = a.deltas_since({})
        clk.advance(0.5)  # the delta is 500ms old when it lands
        b.apply(deltas)
        h = reg.histogram(M.METRIC_GOSSIP_STALENESS_MS)
        assert h["count"] == 1
        assert h["sum"] == pytest.approx(500.0, rel=0.01)
