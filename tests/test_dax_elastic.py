"""Elastic serverless plane tests (dax/): directive protocol edges,
group-commit durability, the crash matrix over the ``dax.*`` kill
sites, SWIM-driven liveness, warm handoff, autoscaling, and the
zero-cost-when-off contract.

``PILOSA_TPU_CRASH_SEED`` (scripts/tier1.sh dax lane) steers the
seed-derived kill plan; default runs use a fixed fallback so the crash
matrix always runs a real plan.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster.client import NodeDownError
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.dax.autoscale import Autoscaler
from pilosa_tpu.dax.computer import Computer
from pilosa_tpu.dax.controller import Controller
from pilosa_tpu.dax.directive import (
    Directive, METHOD_DIFF, METHOD_FULL, METHOD_RESET,
)
from pilosa_tpu.dax.harness import DaxCluster
from pilosa_tpu.dax.storage import Snapshotter, WriteLogger
from pilosa_tpu.obs import metrics as obs_metrics
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.sched.clock import ManualClock
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage.recovery import (
    CrashPlan, DAX_CRASH_SITES, SimulatedCrash,
)

SCHEMA = [{"index": "t", "options": {}, "fields": [
    {"name": "f", "options": {"type": "set"}},
    {"name": "n", "options": {"type": "int"}}]}]


def _full(version, shards, hot=()):
    return Directive(
        version=version, method=METHOD_FULL,
        schema=[dict(t) for t in SCHEMA],
        assigned=[("t", s) for s in shards],
        hot=list(hot)).to_json()


def _ops(k=90, seed=3):
    """Deterministic idempotent workload: set bits + int values over two
    shards (idempotence is what makes crash-retry well-defined)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(k):
        shard = int(rng.integers(0, 2))
        col = shard * SHARD_WIDTH + int(rng.integers(0, 500))
        if i % 4 == 3:
            out.append(("vals", [col], [int(rng.integers(-40, 40))]))
        else:
            out.append(("bits", [int(rng.integers(0, 6))], [col]))
    return out


def _apply_ops(target, ops, start=0):
    """Apply ops[start:] through import_bits/import_values; returns the
    index of the first op that crashed (None = all applied)."""
    for i in range(start, len(ops)):
        kind, a, b = ops[i]
        try:
            if kind == "bits":
                target.import_bits("t", "f", rows=a, cols=b)
            else:
                target.import_values("t", "n", cols=a, values=b)
        except SimulatedCrash:
            return i
    return None


def _oracle(ops):
    api = API()
    api.create_index("t", {})
    api.create_field("t", "f", {"type": "set"})
    api.create_field("t", "n", {"type": "int"})
    _apply_ops(api, ops)
    return api.checksum()


class TestDirectiveProtocol:
    def test_reset_wipes_local_state(self, tmp_path):
        comp = Computer("c0", str(tmp_path))
        comp.apply_directive(_full(1, [0]))
        comp.import_bits("t", "f", rows=[1], cols=[2])
        assert comp.api.holder.indexes
        out = comp.apply_directive(
            Directive(version=2, method=METHOD_RESET,
                      schema=[], assigned=[]).to_json())
        assert out["applied"]
        assert not comp.api.holder.indexes
        assert comp.assigned == set()

    def test_diff_applies_delta_without_schema(self, tmp_path):
        comp = Computer("c0", str(tmp_path))
        comp.apply_directive(_full(1, [0]))
        out = comp.apply_directive(Directive(
            version=2, method=METHOD_DIFF, base_version=1,
            add=[("t", 1)], remove=[("t", 0)],
            assigned=[("t", 1)], schema_changed=False).to_json())
        assert out["applied"]
        assert comp.assigned == {("t", 1)}
        assert "t" in comp.api.holder.indexes  # schema untouched

    def test_diff_after_missed_version_asks_resync(self, tmp_path):
        comp = Computer("c0", str(tmp_path))
        comp.apply_directive(_full(1, [0]))
        out = comp.apply_directive(Directive(
            version=3, method=METHOD_DIFF, base_version=2,
            add=[("t", 1)], assigned=[("t", 0), ("t", 1)],
            schema_changed=False).to_json())
        assert out == {"version": 1, "applied": False, "resync": True}
        # the FULL fallback then lands
        out = comp.apply_directive(_full(3, [0, 1]))
        assert out["applied"]
        assert comp.assigned == {("t", 0), ("t", 1)}

    def test_stale_version_rejected(self, tmp_path):
        comp = Computer("c0", str(tmp_path))
        comp.apply_directive(_full(5, [0]))
        out = comp.apply_directive(_full(4, [0, 1]))
        assert not out["applied"]
        assert comp.assigned == {("t", 0)}


class _FakeComp:
    """Directive sink with scriptable failure for controller tests."""

    def __init__(self):
        self.directives = []
        self.fail = False
        self.resync_once = False

    def apply_directive(self, d):
        if self.fail:
            raise NodeDownError("down")
        if self.resync_once and d["method"] == METHOD_DIFF:
            self.resync_once = False
            return {"version": d["version"], "applied": False,
                    "resync": True}
        self.directives.append(d)
        return {"version": d["version"], "applied": True}


class TestControllerDelivery:
    def _controller(self, tmp_path, registry=None):
        return Controller(str(tmp_path), sleep=lambda s: None,
                          directive_backoff_s=0.0,
                          registry=registry or MetricsRegistry())

    def test_second_push_is_diff(self, tmp_path):
        ctl = self._controller(tmp_path)
        a = _FakeComp()
        ctl.register(Node(id="a", uri=""), computer=a)
        ctl.create_table("t", {}, SCHEMA[0]["fields"])
        ctl.ensure_shard("t", 0)
        methods = [d["method"] for d in a.directives]
        assert methods[0] == METHOD_FULL
        assert METHOD_DIFF in methods[1:]
        last = a.directives[-1]
        assert last["method"] == METHOD_DIFF
        assert last["add"] == [["t", 0]]
        # schema didn't change between the table push and the shard
        # assignment — the diff must not recarry it
        assert last["schemaChanged"] is False
        assert last["schema"] == []

    def test_resync_falls_back_to_full(self, tmp_path):
        reg = MetricsRegistry()
        ctl = self._controller(tmp_path, registry=reg)
        a = _FakeComp()
        ctl.register(Node(id="a", uri=""), computer=a)
        ctl.create_table("t", {}, SCHEMA[0]["fields"])
        a.resync_once = True
        ctl.ensure_shard("t", 0)
        assert a.directives[-1]["method"] == METHOD_FULL
        assert a.directives[-1]["assigned"] == [["t", 0]]
        assert reg.value(obs_metrics.METRIC_DAX_FULL_RESYNCS) == 1

    def test_mid_batch_failure_converges_no_double_delivery(self, tmp_path):
        ctl = self._controller(tmp_path)
        a, b = _FakeComp(), _FakeComp()
        ctl.register(Node(id="a", uri=""), computer=a)
        ctl.register(Node(id="b", uri=""), computer=b)
        ctl.create_table("t", {}, SCHEMA[0]["fields"])
        for s in range(8):
            ctl.ensure_shard("t", s)
        assert {nid for nid in ctl.assignment().values()} == {"a", "b"}
        # b dies; the next broadcast push fails mid-batch and must
        # converge: b buried, its shards on a, a redirected exactly once
        b.fail = True
        ctl.create_field("t", "extra", {"type": "set"})
        assert "b" in ctl.dead
        assert set(ctl.assignment().values()) == {"a"}
        final = Directive.from_json(a.directives[-1]) \
            if a.directives[-1]["method"] == METHOD_FULL else None
        owned = {tuple(x) for x in a.directives[-1]["assigned"]}
        assert owned == {("t", s) for s in range(8)}
        versions = [d["version"] for d in a.directives]
        assert len(versions) == len(set(versions)), \
            "a directive version was delivered twice to the same node"

    def test_rebalance_moves_shards_to_new_node(self, tmp_path):
        ctl = self._controller(tmp_path)
        a = _FakeComp()
        ctl.register(Node(id="a", uri=""), computer=a)
        ctl.create_table("t", {}, SCHEMA[0]["fields"])
        for s in range(12):
            ctl.ensure_shard("t", s)
        b = _FakeComp()
        ctl.register(Node(id="b", uri=""), computer=b)
        moved = ctl.rebalance()
        assert moved > 0
        owners = set(ctl.assignment().values())
        assert owners == {"a", "b"}
        # the loser learned about its removals too
        removed = {tuple(x) for d in a.directives
                   if d["method"] == METHOD_DIFF
                   for x in d.get("remove", [])}
        b_owned = {k for k, v in ctl.assignment().items() if v == "b"}
        assert b_owned <= removed | set()


class TestDropTableResurrection:
    def test_recreate_after_drop_is_empty(self, tmp_path):
        c = DaxCluster(2, shared_dir=str(tmp_path))
        try:
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            c.queryer.import_bits("t", "f", rows=[1, 1, 1],
                                  cols=[5, 10, SHARD_WIDTH + 3])
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == 3
            c.controller.drop_table("t")
            assert c.controller.wl.tables() == []
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == 0
            # cold start over the same dir must not resurrect either
            assert c.controller.wl.shards("t") == []
        finally:
            c.close()


class TestGroupCommit:
    def test_one_fsync_per_shard_not_per_op(self, tmp_path, monkeypatch):
        calls = {"n": 0}
        real = os.fsync

        def counting(fd):
            calls["n"] += 1
            return real(fd)

        # 60 write calls in one request: each appends its own log op,
        # but batch mode pays ONE commit fsync per touched shard
        pql = "".join(f"Set({i}, f=1)" for i in range(60))
        comp = Computer("c0", str(tmp_path / "batch"), snapshot_every=10_000)
        comp.apply_directive(_full(1, [0]))
        monkeypatch.setattr(os, "fsync", counting)
        comp.query_remote("t", pql, shards=[0])
        batch_fsyncs = calls["n"]
        assert batch_fsyncs <= 2, \
            f"group commit issued {batch_fsyncs} fsyncs for one request"
        # the always mode pays per-op — the gap IS the feature
        monkeypatch.setattr(os, "fsync", real)
        comp2 = Computer("c1", str(tmp_path / "always"), sync="always",
                         snapshot_every=10_000)
        comp2.apply_directive(_full(1, [0]))
        monkeypatch.setattr(os, "fsync", counting)
        calls["n"] = 0
        comp2.query_remote("t", pql, shards=[0])
        assert calls["n"] >= 60
        assert batch_fsyncs * 10 < calls["n"]
        # both modes end at the same durable state
        assert len(list(comp.wl.replay("t", 0, 0))) == \
            len(list(comp2.wl.replay("t", 0, 0))) == 60

    def test_torn_tail_stops_replay(self, tmp_path):
        wl = WriteLogger(str(tmp_path))
        for i in range(10):
            wl.append("t", 0, {"k": "bits", "f": "f", "r": [i], "c": [i]})
        wl.commit("t", 0)
        wl.close()
        d = tmp_path / "wl" / "t"
        seg = sorted(p for p in os.listdir(d) if p.startswith("0."))[-1]
        path = d / seg
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 3)
        wl2 = WriteLogger(str(tmp_path))
        ops = list(wl2.replay("t", 0, 0))
        assert len(ops) == 9  # the torn final frame was never acked
        assert [op["r"][0] for op in ops] == list(range(9))

    def test_adopts_seed_era_jsonl(self, tmp_path):
        import json

        d = tmp_path / "wl" / "t"
        os.makedirs(d)
        with open(d / "0.jsonl", "w") as f:
            for i in range(3):
                f.write(json.dumps({"k": "bits", "f": "f",
                                    "r": [i], "c": [i]}) + "\n")
        wl = WriteLogger(str(tmp_path))
        assert wl.shards("t") == [0]
        ops = list(wl.replay("t", 0, 0))
        assert len(ops) == 3
        assert wl.length("t", 0) == 3
        assert not os.path.exists(d / "0.jsonl")  # rewritten, removed
        # appends continue past the adopted prefix
        wl.append("t", 0, {"k": "bits", "f": "f", "r": [9], "c": [9]})
        wl.commit("t", 0)
        assert wl.length("t", 0) == 4


class TestSnapshotter:
    def test_prune_skips_newer_versions(self, tmp_path):
        s = Snapshotter(str(tmp_path))
        s.write("t", 0, 5, {"a": np.array([1, 2, 3])})
        # a slow OLD owner lands its stale snapshot after the new
        # owner's — it must not delete the newer work
        s.write("t", 0, 3, {"a": np.array([9])})
        assert s.latest_version("t", 0) == 5
        v, arrays = s.latest("t", 0)
        assert v == 5 and list(arrays["a"]) == [1, 2, 3]
        s.write("t", 0, 6, {"a": np.array([4])})
        assert s._versions("t", 0) == [6]  # 3 and 5 pruned


class TestCrashMatrix:
    """Every dax.* kill point: the next owner resumes bit-identical to
    an uncrashed oracle once the unacked suffix is retried (set/int ops
    are idempotent — the client-retry contract)."""

    def _run(self, dirpath, plan, ops):
        comp = Computer("c0", dirpath, snapshot_every=8, crash_plan=plan)
        start = 0
        try:
            comp.apply_directive(_full(1, [0, 1]))
        except SimulatedCrash:
            start = 0
        else:
            start = _apply_ops(comp, ops)
        # next owner: clean plan, same shared dir — replay + retry
        comp2 = Computer("c1", dirpath, snapshot_every=8)
        comp2.apply_directive(_full(2, [0, 1]))
        if start is not None:
            assert _apply_ops(comp2, ops, start) is None
        return comp2.api.checksum()

    @pytest.mark.parametrize("site", DAX_CRASH_SITES)
    @pytest.mark.parametrize("at", [1, 2])
    def test_kill_point_resumes_bit_identical(self, tmp_path, site, at):
        ops = _ops()
        golden = _oracle(ops)
        plan = CrashPlan().kill(site, at=at)
        got = self._run(str(tmp_path), plan, ops)
        assert got == golden

    def test_env_seeded_plan(self, tmp_path):
        """The tier1 dax lane's seed (PILOSA_TPU_CRASH_SEED) draws a
        deterministic plan over the dax site tuple — from_env() stays
        the storage lane's, so this lane can't steal its kill points."""
        seed = os.environ.get("PILOSA_TPU_CRASH_SEED", "lane-default")
        plan = CrashPlan.dax_seeded(seed)
        assert plan._arms == CrashPlan.dax_seeded(seed)._arms
        assert all(s in DAX_CRASH_SITES for s in plan._arms)
        ops = _ops()
        golden = _oracle(ops)
        assert self._run(str(tmp_path), plan, ops) == golden

    def test_sites_disjoint_from_other_lanes(self):
        from pilosa_tpu.storage.recovery import (
            CRASH_SITES, STREAM_CRASH_SITES,
        )

        assert not set(DAX_CRASH_SITES) & set(CRASH_SITES)
        assert not set(DAX_CRASH_SITES) & set(STREAM_CRASH_SITES)


class TestMembershipLiveness:
    def test_silence_detected_via_membership(self, tmp_path):
        clock = ManualClock()
        c = DaxCluster(3, shared_dir=str(tmp_path), membership=True,
                       clock=clock)
        try:
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            cols = [s * SHARD_WIDTH + i for s in range(4) for i in range(20)]
            c.queryer.import_bits("t", "f", rows=[1] * len(cols), cols=cols)
            victim = 1
            vid = c.computers[victim].node.id
            had = {k for k, v in c.controller.assignment().items()
                   if v == vid}
            c.silence(victim)
            for _ in range(150):
                c.step()
                clock.advance(0.4)
                if vid in c.controller.dead:
                    break
            assert vid in c.controller.dead, \
                "membership never confirmed the silenced node down"
            assert all(v != vid for v in c.controller.assignment().values())
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == len(cols)
        finally:
            c.close()


class TestWarmHandoff:
    def test_prewarm_builds_stacks_before_ack(self, tmp_path):
        seeder = Computer("c0", str(tmp_path))
        seeder.apply_directive(_full(1, [0, 1]))
        _apply_ops(seeder, _ops())
        reg = MetricsRegistry()
        warm = Computer("c1", str(tmp_path), registry=reg)
        out = warm.apply_directive(_full(2, [0, 1],
                                         hot=[("t", "f"), ("t", "n")]))
        # the ack and the prewarm are one step: by the time applied=True
        # is visible the hot planes are resident
        assert out["applied"]
        assert reg.value(obs_metrics.METRIC_DAX_PREWARM_STACKS) > 0
        assert reg.value(obs_metrics.METRIC_DAX_REPLAY_OPS) > 0

    def test_handoff_off_skips_prewarm(self, tmp_path):
        seeder = Computer("c0", str(tmp_path))
        seeder.apply_directive(_full(1, [0, 1]))
        _apply_ops(seeder, _ops())
        reg = MetricsRegistry()
        cold = Computer("c1", str(tmp_path), warm_handoff=False,
                        registry=reg)
        assert cold.apply_directive(
            _full(2, [0, 1], hot=[("t", "f")]))["applied"]
        assert reg.value(obs_metrics.METRIC_DAX_PREWARM_STACKS) == 0


class TestAutoscaler:
    def _scaler(self, probes, clock, **kw):
        state = {"pool": 2}

        def up():
            state["pool"] += 1
            return state["pool"]

        def down():
            state["pool"] -= 1
            return state["pool"]

        scaler = Autoscaler(
            probes_fn=lambda: probes, scale_up=up, scale_down=down,
            pool_size=lambda: state["pool"], min_nodes=1, max_nodes=4,
            cooldown_s=10.0, queue_high=16, p99_high_ms=250.0,
            settle_ticks=3, clock=clock, registry=MetricsRegistry(), **kw)
        return scaler, state

    def test_scales_up_on_pressure_with_cooldown(self):
        clock = ManualClock()
        probes = {"queue_depth": 99, "leg_p99_ms": 10.0}
        scaler, state = self._scaler(probes, clock)
        assert scaler.tick() == "up"
        assert state["pool"] == 3
        assert scaler.tick() is None  # cooldown holds
        clock.advance(11.0)
        assert scaler.tick() == "up"
        assert state["pool"] == 4
        clock.advance(11.0)
        assert scaler.tick() is None  # max_nodes bound

    def test_scales_down_only_after_settle(self):
        clock = ManualClock()
        probes = {"queue_depth": 0, "leg_p99_ms": 1.0}
        scaler, state = self._scaler(probes, clock)
        assert scaler.tick() is None
        assert scaler.tick() is None
        assert scaler.tick() == "down"  # third consecutive cold tick
        assert state["pool"] == 1
        clock.advance(11.0)
        for _ in range(5):
            scaler.tick()
        assert state["pool"] == 1  # min_nodes floor

    def test_p99_alone_triggers(self):
        clock = ManualClock()
        probes = {"queue_depth": 0, "leg_p99_ms": 900.0}
        scaler, state = self._scaler(probes, clock)
        assert scaler.tick() == "up"


class TestServingPlane:
    def test_cached_reads_and_write_invalidation(self, tmp_path):
        c = DaxCluster(2, shared_dir=str(tmp_path), serving=True)
        try:
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            c.queryer.query("t", "Set(5, f=1)")
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == 1
            hits0 = c.queryer.cache.stats()["hits"]
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == 1
            assert c.queryer.cache.stats()["hits"] == hits0 + 1
            # a write through this front-end invalidates — no stale read
            c.queryer.query("t", "Set(9, f=1)")
            assert c.queryer.query("t", "Count(Row(f=1))")[0] == 2
            # queried fields feed the prewarm set
            assert ("t", "f") in [
                (t, f) for t in c.controller._hot
                for f in c.controller._hot[t]] or \
                "f" in c.controller._hot.get("t", [])
        finally:
            c.close()

    def test_probe_reports_serving_pressure(self, tmp_path):
        c = DaxCluster(2, shared_dir=str(tmp_path), serving=True)
        try:
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            c.queryer.query("t", "Set(5, f=1)")
            c.queryer.query("t", "Count(Row(f=1))")
            p = c.queryer.probe()
            assert p["serving"] is True
            assert p["leg_p99_ms"] > 0.0
            cp = c.controller.probe()
            assert cp["version"] >= 1
            assert cp["directive_age_s"] >= 0.0
        finally:
            c.close()

    def test_scale_up_mid_flight_keeps_results(self, tmp_path):
        c = DaxCluster(2, shared_dir=str(tmp_path), serving=True,
                       snapshot_every=8)
        try:
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            cols = [s * SHARD_WIDTH + i for s in range(4) for i in range(25)]
            c.queryer.import_bits("t", "f", rows=[2] * len(cols), cols=cols)
            assert c.queryer.query("t", "Count(Row(f=2))")[0] == len(cols)
            before = len(c.controller.live_ids())
            c.scale_up()
            assert len(c.controller.live_ids()) == before + 1
            new_id = c.computers[-1].node.id
            assert new_id in set(c.controller.assignment().values()), \
                "rebalance moved nothing to the new node"
            assert c.queryer.query("t", "Count(Row(f=2))")[0] == len(cols)
        finally:
            c.close()


class TestZeroCostOff:
    def test_dax_not_imported_by_classic_paths(self):
        code = ("import pilosa_tpu.api, pilosa_tpu.cluster.node, sys; "
                "print(any(m.startswith('pilosa_tpu.dax') "
                "for m in sys.modules))")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "False"

    def test_no_dax_metrics_without_plane(self):
        reg = MetricsRegistry()
        assert all(not name.startswith("dax_")
                   for (name, _labels) in list(reg._counters)
                   + list(reg._gauges))


class TestObsWiring:
    def test_directive_churn_flight_trigger(self, tmp_path):
        from pilosa_tpu.obs.health import HealthPlane

        clock = ManualClock()
        reg = MetricsRegistry()
        hp = HealthPlane(registry=reg, clock=clock, interval_ms=100.0,
                         directive_churn_bumps=4.0)
        c = DaxCluster(2, shared_dir=str(tmp_path), http=False,
                       clock=clock)
        try:
            hp.attach_dax(queryer=c.queryer, controller=c.controller)
            probe = c.controller.probe()
            assert probe["enabled"] and "recent_directive_bumps" in probe
            hp.timeline.sample()
            assert hp.flight.bundles() == []  # 2 register bumps: normal
            clock.advance(1.0)
            c.controller.create_table("t", {}, SCHEMA[0]["fields"])
            c.controller.create_field("t", "g", {"type": "set"})
            c.controller.create_field("t", "h", {"type": "set"})
            hp.timeline.sample()
            bundles = hp.flight.bundles()
            assert [b["trigger"] for b in bundles] == ["directive_churn"]
            assert "directive bumps" in bundles[0]["reason"]
        finally:
            c.close()
