"""BSI kernel tests against a numpy signed-integer oracle.

Mirrors the reference's BSI range/aggregate tests (reference:
fragment_internal_test.go range/sum/min/max cases, bsi_test.go) but
property-style: encode random signed values, compare every predicate
against numpy on the raw values.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as B
from pilosa_tpu.ops import bsi as S

WORDS = 1 << 9
NBITS = WORDS * 32


def make_data(rng, n=3000, lo=-5000, hi=5000):
    cols = np.unique(rng.integers(0, NBITS, size=n))
    vals = rng.integers(lo, hi, size=cols.size)
    depth = max(S.bits_needed(int(vals.min())), S.bits_needed(int(vals.max())))
    planes = S.encode_values(cols, vals, depth, WORDS)
    return cols, vals, planes


OPS = {
    S.EQ: lambda v, c: v == c,
    S.NE: lambda v, c: v != c,
    S.LT: lambda v, c: v < c,
    S.LE: lambda v, c: v <= c,
    S.GT: lambda v, c: v > c,
    S.GE: lambda v, c: v >= c,
}


@pytest.mark.parametrize("op", list(OPS))
@pytest.mark.parametrize("c", [-6000, -4999, -37, -1, 0, 1, 42, 4999, 6000])
def test_compare(rng, op, c):
    cols, vals, planes = make_data(rng)
    got = set(int(x) for x in B.plane_to_bits(np.asarray(S.bsi_compare(planes, op, c))))
    expect = set(int(x) for x in cols[OPS[op](vals, c)])
    assert got == expect, (op, c)


@pytest.mark.parametrize("a,b", [(-100, 100), (0, 0), (-5000, 5000), (40, 30), (-6000, 6000)])
def test_between(rng, a, b):
    cols, vals, planes = make_data(rng)
    got = set(
        int(x) for x in B.plane_to_bits(np.asarray(S.bsi_compare(planes, S.BETWEEN, a, b)))
    )
    expect = set(int(x) for x in cols[(vals >= a) & (vals <= b)])
    assert got == expect


def test_sum_count(rng):
    cols, vals, planes = make_data(rng)
    full = B.bits_to_plane(cols, WORDS)
    total, count = S.bsi_sum(planes, full)
    assert total == int(vals.sum())
    assert count == cols.size
    # Filtered by half the columns.
    filt_cols = cols[::2]
    filt = B.bits_to_plane(filt_cols, WORDS)
    total, count = S.bsi_sum(planes, filt)
    assert total == int(vals[::2].sum())
    assert count == filt_cols.size


def test_sum_large_values(rng):
    # Values beyond int32 must be exact (host assembles 64-bit from plane
    # popcounts).
    cols = np.array([1, 2, 3])
    vals = np.array([2**40, -(2**41), 7])
    planes = S.encode_values(cols, vals, 42, WORDS)
    total, count = S.bsi_sum(planes, B.bits_to_plane(cols, WORDS))
    assert total == int(2**40 - 2**41 + 7)
    assert count == 3


@pytest.mark.parametrize(
    "vals",
    [
        [5, 3, 9, 3],
        [-5, -3, -9],
        [-5, 0, 5],
        [0, 0],
        [7],
        [-(2**40), 2**40, 12],
    ],
)
def test_min_max(rng, vals):
    vals = np.array(vals, dtype=np.int64)
    cols = np.arange(10, 10 + vals.size) * 7
    depth = max(S.bits_needed(int(v)) for v in vals)
    planes = S.encode_values(cols, vals, depth, WORDS)
    full = B.bits_to_plane(cols, WORDS)
    mn, mn_cnt, tot = S.bsi_min(planes, full)
    mx, mx_cnt, _ = S.bsi_max(planes, full)
    assert mn == int(vals.min())
    assert mx == int(vals.max())
    assert mn_cnt == int((vals == vals.min()).sum())
    assert mx_cnt == int((vals == vals.max()).sum())
    assert tot == vals.size


def test_min_max_filtered(rng):
    cols, vals, planes = make_data(rng)
    filt_cols = cols[1::3]
    filt = B.bits_to_plane(filt_cols, WORDS)
    sub = vals[1::3]
    mn, _, _ = S.bsi_min(planes, filt)
    mx, _, _ = S.bsi_max(planes, filt)
    assert mn == int(sub.min())
    assert mx == int(sub.max())


def test_empty_filter(rng):
    cols, vals, planes = make_data(rng)
    empty = np.zeros(WORDS, dtype=np.uint32)
    assert S.bsi_sum(planes, empty) == (0, 0)
    assert S.bsi_min(planes, empty) == (0, 0, 0)
    assert S.bsi_max(planes, empty) == (0, 0, 0)


def test_compare_random_fuzz(rng):
    # Broad fuzz across many constants, like the reference's roaring fuzzers
    # (roaring/fuzz_test.go).
    cols, vals, planes = make_data(rng, n=500, lo=-50, hi=50)
    for c in range(-55, 56, 7):
        for op, fn in OPS.items():
            got = set(
                int(x)
                for x in B.plane_to_bits(np.asarray(S.bsi_compare(planes, op, c)))
            )
            assert got == set(int(x) for x in cols[fn(vals, c)]), (op, c)
