"""Block-compressed device-resident bitmap tiles (ops/ctiles.py +
core/stacked.py integration).

The invariants are the real ones: every compressed read path is
bit-identical to the dense oracle (decode, tile-skipping row_counts, the
active-tile BSI compare, the full executor battery), the
``PILOSA_TPU_COMPRESS=0`` kill switch does zero work (no compressed
blocks, no metric ticks), and the chunked ingest scatter matches the
per-row native loop for imports wider than one chunk.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from pilosa_tpu.core import FieldOptions, FieldType, Holder
from pilosa_tpu.core import stacked as stx
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.ops import bitmap as B
from pilosa_tpu.ops import bsi as S
from pilosa_tpu.ops import ctiles as C
from pilosa_tpu.ops import pallas_util as PU
from pilosa_tpu.ops import scatter as SC
from pilosa_tpu.pql import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture(autouse=True)
def _clean_strikes():
    PU.reset_failures()
    yield
    PU.reset_failures()


@pytest.fixture
def forced(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_COMPRESS", "1")


@pytest.fixture
def killed(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_COMPRESS", "0")


def dispatch_count(kernel: str) -> float:
    return M.REGISTRY.value(M.METRIC_OPS_PALLAS_DISPATCH,
                            kernel=kernel) or 0.0


def fallback_count(kernel: str, why: str) -> float:
    return M.REGISTRY.value(M.METRIC_OPS_PALLAS_FALLBACK, kernel=kernel,
                            why=why) or 0.0


def _sparse_block(rng, rows, words, n_bits=40):
    host = np.zeros((rows, words), dtype=np.uint32)
    host[rng.integers(0, rows, n_bits), rng.integers(0, words, n_bits)] = \
        rng.integers(1, 2 ** 32, n_bits, dtype=np.uint32)
    return host


# ---------------------------------------------------------------------------
# classify / decode round trip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(2, 1), (3, 7), (8, 512), (16, 1000),
                                   (5, 2048), (1, 4096)])
def test_decode_roundtrip(forced, shape):
    rng = np.random.default_rng(hash(shape) % 2 ** 31)
    host = _sparse_block(rng, *shape)
    host[0, :] = 0  # guarantee at least one all-zero row
    cb = C.maybe_compress(host, kind="set")
    assert cb is not None
    assert np.array_equal(np.asarray(cb.decode()), host)
    # row-subset decode
    sub = [0, shape[0] - 1]
    assert np.array_equal(np.asarray(cb.decode(rows=sub)), host[sub])


def test_tags_zero_run_dense(forced):
    words = 2048
    zero = np.zeros((4, words), dtype=np.uint32)
    cb = C.maybe_compress(zero, kind="set")
    assert cb.dense_tiles == 0 and cb.run_tiles == 0 and cb.zero_tiles > 0
    assert np.asarray(cb.row_counts()).tolist() == [0] * 4

    ones = np.full((4, words), 0xFFFFFFFF, dtype=np.uint32)
    cb = C.maybe_compress(ones, kind="set")
    assert cb.dense_tiles == 0 and cb.run_tiles == 4 * cb.n_tiles
    assert cb.const_uniform
    assert np.array_equal(np.asarray(cb.decode()), ones)
    assert np.asarray(cb.row_counts()).tolist() == [words * 32] * 4

    rng = np.random.default_rng(3)
    mixed = np.zeros((4, words), dtype=np.uint32)
    mixed[1] = 0xFFFFFFFF
    mixed[2, :100] = rng.integers(1, 2 ** 32, 100, dtype=np.uint32)
    cb = C.maybe_compress(mixed, kind="set")
    assert cb.zero_tiles and cb.run_tiles and cb.dense_tiles
    assert np.array_equal(np.asarray(cb.decode()), mixed)


def test_unaligned_width_run_rows_stay_exact(forced):
    # a non-tile-multiple width zero-pads the last tile: an all-ones row
    # must still decode and count exactly (its last tile is dense, not
    # a truncated run)
    words = C.TILE_WORDS + 100
    host = np.full((3, words), 0xFFFFFFFF, dtype=np.uint32)
    cb = C.maybe_compress(host, kind="set")
    assert np.array_equal(np.asarray(cb.decode()), host)
    assert np.asarray(cb.row_counts()).tolist() == [words * 32] * 3


# ---------------------------------------------------------------------------
# tile-skipping row_counts vs the dense oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("filtered", [False, True])
def test_row_counts_parity(forced, monkeypatch, filtered):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    rng = np.random.default_rng(7)
    host = _sparse_block(rng, 16, 4096, n_bits=200)
    cb = C.maybe_compress(host, kind="set")
    filt = None
    if filtered:
        filt = jnp.asarray(rng.integers(
            0, 2 ** 32, 4096, dtype=np.uint32).astype(np.uint32))
    d0 = dispatch_count("ctile_count")
    got = np.asarray(cb.row_counts(filt))
    want = np.asarray(B.row_counts(host, filt))
    assert np.array_equal(got, want)
    assert dispatch_count("ctile_count") == d0 + 1, \
        "forced mode must take the Pallas ctile_count kernel"


def test_row_counts_parity_pallas_killed(forced, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    rng = np.random.default_rng(8)
    host = _sparse_block(rng, 16, 4096, n_bits=200)
    cb = C.maybe_compress(host, kind="set")
    d0 = dispatch_count("ctile_count")
    got = np.asarray(cb.row_counts())
    assert np.array_equal(got, np.asarray(B.row_counts(host)))
    assert dispatch_count("ctile_count") == d0, \
        "XLA compressed path must not tick the Pallas dispatch counter"


def test_nonuniform_const_filter_falls_back_exact(forced):
    # whole-tile runs of an arbitrary word have no closed form under a
    # filter: the scan must decode and still be bit-identical
    host = np.full((4, 2048), 0xDEADBEEF, dtype=np.uint32)
    cb = C.maybe_compress(host, kind="set")
    assert not cb.const_uniform
    rng = np.random.default_rng(9)
    filt = jnp.asarray(rng.integers(
        0, 2 ** 32, 2048, dtype=np.uint32).astype(np.uint32))
    f0 = M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="const",
                          kind="scan") or 0.0
    got = np.asarray(cb.row_counts(filt))
    assert np.array_equal(got, np.asarray(B.row_counts(host, filt)))
    assert (M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="const",
                             kind="scan") or 0.0) == f0 + 1


# ---------------------------------------------------------------------------
# policy: ratio rule, size floor, kill switch
# ---------------------------------------------------------------------------


@pytest.fixture
def single_device_mesh():
    # auto-mode policy tests: conftest boots 8 virtual devices, whose
    # mesh guard would mask the size/ratio rules under scrutiny
    from pilosa_tpu.parallel import mesh as PM
    import jax

    PM.set_engine_mesh(PM.analytics_mesh(jax.devices()[:1]))
    yield
    PM.set_engine_mesh(None)


def test_incompressible_block_stays_dense(single_device_mesh, monkeypatch):
    monkeypatch.delenv("PILOSA_TPU_COMPRESS", raising=False)
    rng = np.random.default_rng(10)
    host = rng.integers(0, 2 ** 32, (32, 1024),
                        dtype=np.uint32).astype(np.uint32)  # 128 KiB random
    f0 = M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="ratio",
                          kind="set") or 0.0
    assert C.maybe_compress(host, kind="set") is None
    assert (M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="ratio",
                             kind="set") or 0.0) == f0 + 1


def test_small_block_stays_dense_by_default(monkeypatch):
    monkeypatch.delenv("PILOSA_TPU_COMPRESS", raising=False)
    host = np.zeros((8, 32), dtype=np.uint32)  # 1 KiB << MIN_BYTES
    assert C.maybe_compress(host, kind="set") is None


def test_multi_device_mesh_stays_dense_in_auto_mode(monkeypatch):
    # conftest's 8 virtual devices: auto mode must keep mesh-sharded
    # stacks dense (placement rule), metered as why="mesh"
    monkeypatch.delenv("PILOSA_TPU_COMPRESS", raising=False)
    from pilosa_tpu.parallel.mesh import engine_mesh

    if engine_mesh().devices.size <= 1:
        pytest.skip("needs the virtual multi-device mesh")
    host = np.zeros((16, 65536), dtype=np.uint32)
    f0 = M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="mesh",
                          kind="set") or 0.0
    assert C.maybe_compress(host, kind="set") is None
    assert M.REGISTRY.value(M.METRIC_COMPRESS_FALLBACK, why="mesh",
                            kind="set") == f0 + 1


def _compress_series(snap: dict) -> dict:
    return {k: v for section in ("counters", "gauges")
            for k, v in snap[section].items()
            if k.startswith("device_compress")}


def test_kill_switch_zero_work_zero_ticks(killed):
    before = _compress_series(M.REGISTRY.snapshot())
    host = np.zeros((64, 4096), dtype=np.uint32)  # would compress hugely
    assert C.maybe_compress(host, kind="set") is None
    assert _compress_series(M.REGISTRY.snapshot()) == before, \
        "the kill switch must not move any compress metric"


# ---------------------------------------------------------------------------
# stacked integration: the full read surface, compressed vs kill switch
# ---------------------------------------------------------------------------


QUERIES = [
    "Count(Row(f=3))",
    "TopN(f, n=10)",
    "Count(Row(v > 5))",
    "Count(Row(v < -20))",
    "Count(Row(v == 7))",
    "Count(Row(v != 7))",
    "Count(Row(v >= -100))",
    "Count(Row(-10 < v < 20))",
    "Count(Intersect(Row(f=1), Row(v >= 0)))",
    "GroupBy(Rows(f))",
    "Min(field=v)",
    "Max(field=v)",
    "Sum(field=v)",
]


def _battery(monkeypatch, mode: str):
    monkeypatch.setenv("PILOSA_TPU_COMPRESS", mode)
    h = Holder()
    e = Executor(h)
    h.create_index("i").create_field("f")
    h.index("i").create_field(
        "v", FieldOptions(type=FieldType.INT, min=-100, max=100))
    f = h.index("i").field("f")
    v = h.index("i").field("v")
    rng = np.random.default_rng(5)
    for s in range(2):
        rows = rng.integers(0, 30, 400)
        cols = s * SHARD_WIDTH + rng.integers(0, SHARD_WIDTH, 400)
        f.import_bits(rows.tolist(), cols.tolist())
        vc = s * SHARD_WIDTH + rng.integers(0, SHARD_WIDTH, 200)
        v.set_values(vc.tolist(), rng.integers(-100, 100, 200).tolist())
    out = [e.execute("i", q) for q in QUERIES]
    # a write between queries exercises the advance path (compressed
    # blocks decay to dense device-side), then the battery again
    e.execute("i", "Set(12345, f=3)")
    out.extend(e.execute("i", q) for q in QUERIES)
    return h, f, repr(out)


def _built_blocks():
    return (M.REGISTRY.value(M.METRIC_COMPRESS_BLOCKS, kind="set"),
            M.REGISTRY.value(M.METRIC_COMPRESS_BLOCKS, kind="bsi"))


def test_executor_battery_bit_identical(monkeypatch):
    c0 = _built_blocks()
    _, _, compressed = _battery(monkeypatch, "1")
    c1 = _built_blocks()
    assert c1[0] > c0[0] and c1[1] > c0[1], \
        "forced mode built no compressed-resident blocks"
    _, _, dense = _battery(monkeypatch, "0")
    assert _built_blocks() == c1, "kill switch still built compressed blocks"
    assert compressed == dense


def test_compressed_stack_charges_fewer_bytes(monkeypatch):
    d0 = M.REGISTRY.value(M.METRIC_COMPRESS_DENSE_BYTES)
    s0 = M.REGISTRY.value(M.METRIC_COMPRESS_STORED_BYTES)
    _battery(monkeypatch, "1")
    dense = M.REGISTRY.value(M.METRIC_COMPRESS_DENSE_BYTES) - d0
    stored = M.REGISTRY.value(M.METRIC_COMPRESS_STORED_BYTES) - s0
    # every random bit densifies its whole tile, so this fixture is a
    # worst case for tiling; 2x is still a clear win (the bench asserts
    # the 10x headline on realistically clustered rows)
    assert dense > 0 and stored < dense / 2, \
        "sparse fixture should compress at least 2x"
    # the budget gauge mirrors the compressed accounting
    assert M.REGISTRY.value(M.METRIC_DEVICE_BUDGET_RESIDENT_BYTES) \
        == stx.BUDGET.used


def test_bsi_compare_fast_path_parity(forced):
    rng = np.random.default_rng(11)
    depth, words = 7, 8192
    cols = rng.integers(0, words * 32, 300)
    vals = rng.integers(-50, 50, 300)
    planes = np.asarray(S.encode_values(
        np.asarray(cols), np.asarray(vals), depth, words))
    cb = C.maybe_compress(planes, kind="bsi")
    assert cb is not None
    dense = jnp.asarray(planes)
    for op, v, v2 in [("eq", 3, None), ("ne", 3, None), ("lt", 0, None),
                      ("le", -5, None), ("gt", 10, None), ("ge", -49, None),
                      ("between", -10, 20)]:
        want = np.asarray(S.bsi_compare(dense, op, v, v2))
        got = np.asarray(C.bsi_compare_compressed(cb, op, v, v2))
        assert np.array_equal(got, want), op


def test_bsi_compare_empty_stack_short_circuits(forced):
    planes = np.zeros((S.OFFSET + 3, 4096), dtype=np.uint32)
    cb = C.maybe_compress(planes, kind="bsi")
    assert cb.active_tiles.size == 0
    out = np.asarray(C.bsi_compare_compressed(cb, "eq", 0))
    assert not out.any()


def test_metrics_exposition(monkeypatch):
    # satellite: DeviceBudget's own gauges/counters + the compress series
    # must all render as prometheus exposition
    monkeypatch.setenv("PILOSA_TPU_COMPRESS", "1")
    monkeypatch.setattr(stx, "BUDGET", stx.DeviceBudget(1 << 20))
    rng = np.random.default_rng(12)
    for seed in range(3):  # several stacks force evictions under 1 MiB
        host = _sparse_block(rng, 16, 65536, n_bits=100)
        cb = C.maybe_compress(host, kind="set")
        stx.BUDGET.charge(("t", seed), cb.dense_nbytes, lambda: None)
        cb.row_counts()
    text = M.REGISTRY.prometheus_text()
    for name in ("device_budget_resident_bytes",
                 "device_budget_evictions_total",
                 "device_compress_blocks_total",
                 "device_compress_dense_bytes_total",
                 "device_compress_stored_bytes_total",
                 "device_compress_ratio",
                 "device_compress_tiles_skipped_total"):
        assert name in text, name


# ---------------------------------------------------------------------------
# satellite: chunked ingest scatter
# ---------------------------------------------------------------------------


def test_why_not_ingest_chunk_rules(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    assert SC.why_not_ingest(0, 1, 512) == "shape"
    assert SC.why_not_ingest(10, 1, SC.MAX_FLAT_WORDS * 2) == "shape"
    # multi-chunk totals are now eligible (the old caps rejected them)
    rows = 2 * (SC.MAX_FLAT_WORDS // 512)
    assert SC.why_not_ingest(100, rows, 512) is None
    # ... but the interpreter keeps the native loop beyond a few chunks
    huge = 100 * (SC.MAX_FLAT_WORDS // 512)
    assert SC.why_not_ingest(100, huge, 512) == "interpret"


def test_scatter_chunked_matches_native_oracle(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    monkeypatch.setattr(SC, "MAX_FLAT_WORDS", 1 << 10)  # 2 rows per chunk
    chunks = []
    real = SC._scatter_chunk

    def spy(planes, uslots, addr, masks):
        chunks.append(len(uslots))
        return real(planes, uslots, addr, masks)

    monkeypatch.setattr(SC, "_scatter_chunk", spy)
    rng = np.random.default_rng(13)
    words, rows = 512, 9
    planes = np.zeros((rows, words), dtype=np.uint32)
    planes[rng.integers(0, rows, 50), rng.integers(0, words, 50)] = \
        rng.integers(1, 2 ** 32, 50, dtype=np.uint32)
    want = planes.copy()
    slots = rng.integers(0, rows, 400)
    cols = rng.integers(0, words * 32, 400)
    d0 = dispatch_count("ingest_scatter")
    changed = SC.scatter_new_bits_bulk(planes, slots, cols)
    newbits = 0
    for s, c in zip(slots, cols):
        w, b = divmod(int(c), 32)
        if not (want[s, w] >> np.uint32(b)) & 1:
            newbits += 1
            want[s, w] |= np.uint32(1 << b)
    assert changed == newbits
    assert np.array_equal(planes, want)
    assert len(chunks) >= 4, chunks  # 9 touched rows, 2 per chunk
    assert all(c <= 2 for c in chunks), chunks
    assert dispatch_count("ingest_scatter") == d0 + 1


def test_import_bits_multi_row_stays_on_device(monkeypatch):
    # 3 distinct rows x WORDS_PER_SHARD used to be rejected wholesale
    # (n_rows*words over the flat cap); the chunked grid keeps it
    # on-device and bit-identical
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    h = Holder()
    e = Executor(h)
    h.create_index("i").create_field("f")
    f = h.index("i").field("f")
    rng = np.random.default_rng(14)
    rows = rng.integers(0, 3, 90).tolist()
    cols = rng.integers(0, SHARD_WIDTH, 90).tolist()
    d0 = dispatch_count("ingest_scatter")
    f.import_bits(rows, cols)
    assert dispatch_count("ingest_scatter") > d0, \
        "multi-row import fell off the device scatter path"
    want = {r: len({c for rr, c in zip(rows, cols) if rr == r})
            for r in set(rows)}
    for r, n in want.items():
        assert e.execute("i", f"Count(Row(f={r}))")[0] == n
