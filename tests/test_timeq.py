"""Time-quantum view tests vs a brute-force coverage oracle.

Mirrors the reference's time tests (time_internal_test.go) — write views
per timestamp and minimal covering sets for ranges.
"""

import datetime as dt

import pytest

from pilosa_tpu.core import timeq


def test_views_by_time():
    t = dt.datetime(2010, 1, 2, 3)
    assert timeq.views_by_time(t, "YMDH") == [
        "standard_2010",
        "standard_201001",
        "standard_20100102",
        "standard_2010010203",
    ]
    assert timeq.views_by_time(t, "D") == ["standard_20100102"]


def test_invalid_quantum():
    for bad in ("X", "YD", "HY", "YMH"):
        with pytest.raises(ValueError):
            timeq.validate_quantum(bad)


def _oracle_hours(views):
    """Expand a view list to the set of hours it covers."""
    hours = set()
    for v in views:
        stamp = v.split("_", 1)[1]
        fmt = {4: "%Y", 6: "%Y%m", 8: "%Y%m%d", 10: "%Y%m%d%H"}[len(stamp)]
        unit = {4: "Y", 6: "M", 8: "D", 10: "H"}[len(stamp)]
        start = dt.datetime.strptime(stamp, fmt)
        end = timeq._next(start, unit)
        t = start
        while t < end:
            hours.add(t)
            t += dt.timedelta(hours=1)
    return hours


def _expected_hours(lo, hi):
    out = set()
    t = lo
    while t < hi:
        out.add(t)
        t += dt.timedelta(hours=1)
    return out


@pytest.mark.parametrize(
    "lo,hi",
    [
        (dt.datetime(2010, 1, 1), dt.datetime(2010, 1, 1)),
        (dt.datetime(2010, 1, 1), dt.datetime(2011, 1, 1)),
        (dt.datetime(2010, 11, 28, 5), dt.datetime(2012, 3, 2, 7)),
        (dt.datetime(2010, 1, 1), dt.datetime(2010, 1, 2)),
        (dt.datetime(2010, 12, 31, 23), dt.datetime(2011, 1, 1, 1)),
        (dt.datetime(2009, 6, 15, 13), dt.datetime(2009, 6, 15, 14)),
    ],
)
def test_range_cover_exact_ymdh(lo, hi):
    views = timeq.views_by_time_range(lo, hi, "YMDH")
    assert _oracle_hours(views) == _expected_hours(lo, hi)
    # No duplicate coverage: total hours across views == exact count.
    assert sum(len(_oracle_hours([v])) for v in views) == len(_expected_hours(lo, hi))


def test_range_cover_snaps_to_finest_unit():
    # Quantum "YMD": sub-day boundaries snap outward to whole days.
    views = timeq.views_by_time_range(
        dt.datetime(2010, 1, 1, 5), dt.datetime(2010, 1, 2, 7), "YMD"
    )
    assert views == ["standard_20100101", "standard_20100102"]


def test_range_uses_coarse_views():
    views = timeq.views_by_time_range(
        dt.datetime(2010, 1, 1), dt.datetime(2012, 1, 1), "YMDH"
    )
    assert views == ["standard_2010", "standard_2011"]


def test_range_month_edges():
    views = timeq.views_by_time_range(
        dt.datetime(2010, 11, 1), dt.datetime(2011, 2, 1), "YM"
    )
    assert views == ["standard_201011", "standard_201012", "standard_201101"]
