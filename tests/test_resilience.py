"""Fan-out resilience tests: cancellation tokens, latency tracking,
circuit-breaker state machine, deterministic fault injection, hedged-leg
races, adaptive timeouts, and end-to-end cluster behavior under a
FaultPlan (straggler hedging bit-identical to the no-fault oracle,
replica failover under flapping nodes, breaker-driven recovery).

scripts/tier1.sh re-runs this file under two fixed values of
PILOSA_TPU_FAULT_SEED — every test must hold for ANY seed: seeds only
steer `prob` rules, and tests that pin exact fault sequences construct
their plans with explicit seeds."""

import random
import threading
import time

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    CancellationToken, CircuitBreaker, FaultPlan, InjectedFault,
    LatencyTracker, LegCancelled, LocalCluster, NodeDownError, Resilience,
)
from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.executor import ClusterExecutor
from pilosa_tpu.cluster.resilience import (
    BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN,
)
from pilosa_tpu.cluster.topology import ClusterSnapshot, Node
from pilosa_tpu.config import Config
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.sched import Deadline, ManualClock, deadline_scope
from pilosa_tpu.shardwidth import SHARD_WIDTH


def make_nodes(n):
    return [Node(id=f"node{i}", uri=f"http://host{i}") for i in range(n)]


class TestCancellationToken:
    def test_starts_clear_and_cancels(self):
        tok = CancellationToken(timeout_s=1.5)
        assert not tok.cancelled
        assert tok.timeout_s == 1.5
        assert tok.wait(0.0) is False
        tok.cancel()
        assert tok.cancelled
        # wait returns immediately once cancelled, whatever the timeout
        assert tok.wait(60.0) is True

    def test_cancel_wakes_a_waiter(self):
        tok = CancellationToken()
        woke = []
        t = threading.Thread(target=lambda: woke.append(tok.wait(5.0)))
        t.start()
        tok.cancel()
        t.join(timeout=2.0)
        assert woke == [True]


class TestLatencyTracker:
    def test_empty_returns_none(self):
        tr = LatencyTracker()
        assert tr.percentile("a", 99.0) is None

    def test_exact_percentiles_per_node(self):
        tr = LatencyTracker(window=32)
        for v in [3, 1, 2, 5, 4, 7, 6, 9, 8, 10]:
            tr.observe("a", float(v))
        assert tr.percentile("a", 0.0) == 1.0
        assert tr.percentile("a", 50.0) == 6.0  # idx int(0.5*10)=5
        assert tr.percentile("a", 100.0) == 10.0

    def test_unknown_node_falls_back_to_global_window(self):
        tr = LatencyTracker()
        tr.observe("a", 2.0)
        tr.observe("b", 4.0)
        assert tr.percentile("never-seen", 100.0) == 4.0

    def test_window_bounds_samples(self):
        tr = LatencyTracker(window=4)
        for v in range(1, 11):
            tr.observe("a", float(v))
        # only the last 4 samples (7..10) survive
        assert tr.percentile("a", 0.0) == 7.0
        assert tr.percentile("a", 100.0) == 10.0


class TestCircuitBreaker:
    def _mk(self, threshold=2, open_s=5.0):
        clk = ManualClock()
        reg = MetricsRegistry()
        transitions = []
        br = CircuitBreaker(
            threshold=threshold, open_s=open_s, clock=clk, registry=reg,
            on_transition=lambda n, frm, to: transitions.append((frm, to)))
        return br, clk, reg, transitions

    def test_full_state_machine(self):
        br, clk, reg, transitions = self._mk()
        assert br.state("x") == BREAKER_CLOSED
        assert br.allow("x") is True
        br.record_failure("x")
        assert br.state("x") == BREAKER_CLOSED  # below threshold
        br.record_failure("x")
        assert br.state("x") == BREAKER_OPEN
        assert br.allow("x") is False  # open and not yet expired
        clk.advance(5.0)
        assert br.allow("x") is True  # the half-open probe grant
        assert br.state("x") == BREAKER_HALF_OPEN
        br.record_failure("x")  # probe failed: straight back to open
        assert br.state("x") == BREAKER_OPEN
        clk.advance(5.0)
        assert br.allow("x") is True
        br.record_success("x")
        assert br.state("x") == BREAKER_CLOSED
        assert transitions == [
            (BREAKER_CLOSED, BREAKER_OPEN),
            (BREAKER_OPEN, BREAKER_HALF_OPEN),
            (BREAKER_HALF_OPEN, BREAKER_OPEN),
            (BREAKER_OPEN, BREAKER_HALF_OPEN),
            (BREAKER_HALF_OPEN, BREAKER_CLOSED),
        ]
        # observable via metrics: gauge back at closed=0, counters per state
        assert reg.value(M.METRIC_CLUSTER_BREAKER_STATE, node="x") == 0.0
        assert reg.value(M.METRIC_CLUSTER_BREAKER_TRANSITIONS,
                         node="x", to=BREAKER_OPEN) == 2.0
        assert reg.value(M.METRIC_CLUSTER_BREAKER_TRANSITIONS,
                         node="x", to=BREAKER_CLOSED) == 1.0

    def test_single_probe_with_expiring_grant(self):
        br, clk, _, _ = self._mk(threshold=1, open_s=2.0)
        br.record_failure("x")
        clk.advance(2.0)
        assert br.allow("x") is True  # probe granted
        assert br.allow("x") is False  # second leg vetoed while probing
        # the probing query died without reporting; grant expires
        clk.advance(2.0)
        assert br.allow("x") is True

    def test_success_resets_failure_streak(self):
        br, _, _, _ = self._mk(threshold=2)
        br.record_failure("x")
        br.record_success("x")
        br.record_failure("x")
        assert br.state("x") == BREAKER_CLOSED  # streak broken, not 2-in-a-row

    def test_nodes_are_independent(self):
        br, _, _, _ = self._mk(threshold=1)
        br.record_failure("x")
        assert br.state("x") == BREAKER_OPEN
        assert br.state("y") == BREAKER_CLOSED
        assert br.allow("y") is True


class TestFaultPlan:
    def test_drop_is_a_transport_error(self):
        plan = FaultPlan(seed=1).drop("a")
        with pytest.raises(InjectedFault) as ei:
            plan.on_request("a")
        assert isinstance(ei.value, OSError)
        assert plan.events == [("a", 0, "drop")]

    def test_untargeted_nodes_pass_and_do_not_count(self):
        plan = FaultPlan(seed=1).drop("a")
        for _ in range(3):
            plan.on_request("b")  # no rules for b: no fault, no count
        assert plan.seen("b") == 0
        assert plan.events == []

    def test_first_and_count_window(self):
        plan = FaultPlan(seed=1).drop("a", first=2, count=2)
        hit = []
        for k in range(6):
            try:
                plan.on_request("a")
                hit.append(False)
            except InjectedFault:
                hit.append(True)
        assert hit == [False, False, True, True, False, False]

    def test_flap_period(self):
        plan = FaultPlan(seed=1).flap("a", period=3)
        hit = []
        for _ in range(7):
            try:
                plan.on_request("a")
                hit.append(False)
            except InjectedFault:
                hit.append(True)
        assert hit == [True, False, False, True, False, False, True]
        assert [e[2] for e in plan.events] == ["flap"] * 3

    def test_prob_rules_are_seed_deterministic(self):
        def run(seed):
            plan = FaultPlan(seed=seed).drop("a", prob=0.5)
            out = []
            for _ in range(32):
                try:
                    plan.on_request("a")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b = run(3), run(3)
        assert a == b  # same seed, same request order -> same faults
        assert 0 < sum(a) < 32  # prob actually gates (not all/none)
        # and the per-request decision stream is a pure function of
        # (seed, node, k) — independent of PYTHONHASHSEED / process
        assert FaultPlan(seed=3)._hit_rng("a", 0)() == \
            FaultPlan(seed=3)._hit_rng("a", 0)()

    def test_seed_defaults_from_env(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_FAULT_SEED", "41")
        assert FaultPlan().seed == 41
        monkeypatch.delenv("PILOSA_TPU_FAULT_SEED")
        assert FaultPlan().seed == 0

    def test_delay_uses_injectable_sleep(self):
        slept = []
        plan = FaultPlan(seed=1, sleep=slept.append).delay("a", 0.25)
        plan.on_request("a")
        assert slept == [0.25]
        assert plan.events == [("a", 0, "delay")]

    def test_delay_with_cancelled_token_raises_leg_cancelled(self):
        plan = FaultPlan(seed=1).delay("a", 30.0)
        tok = CancellationToken()
        tok.cancel()
        with pytest.raises(LegCancelled):
            plan.on_request("a", token=tok)  # returns immediately, no sleep

    def test_clear_disarms(self):
        plan = FaultPlan(seed=1).drop("a").drop("b")
        plan.clear("a")
        plan.on_request("a")  # no longer armed
        with pytest.raises(InjectedFault):
            plan.on_request("b")
        plan.clear()
        plan.on_request("b")

    def test_seen_tracks_armed_requests(self):
        plan = FaultPlan(seed=1).delay("a", 0.0)
        assert plan.seen("a") == 0
        plan.on_request("a")
        plan.on_request("a")
        assert plan.seen("a") == 2


class TestClientRetry:
    # nothing listens on port 1: instant connection-refused
    DEAD_URL = "http://127.0.0.1:1/x"

    def test_jittered_backoff_between_retries(self):
        slept = []
        c = InternalClient(timeout=0.2, retries=2, backoff=0.05,
                           sleep=slept.append, rng=random.Random(0))
        with pytest.raises(NodeDownError):
            c._request("GET", self.DEAD_URL)
        # full-jitter over [0.5x, 1.5x) of backoff * 2^attempt
        assert len(slept) == 2
        assert 0.025 <= slept[0] < 0.075
        assert 0.05 <= slept[1] < 0.15

    def test_jitter_draws_come_from_injected_rng(self):
        r = random.Random(7)
        want = [0.05 * (0.5 + r.random()), 0.1 * (0.5 + r.random())]
        slept = []
        c = InternalClient(timeout=0.2, retries=2, backoff=0.05,
                           sleep=slept.append, rng=random.Random(7))
        with pytest.raises(NodeDownError):
            c._request("GET", self.DEAD_URL)
        assert slept == pytest.approx(want)

    def test_cancelled_token_aborts_before_any_attempt(self):
        slept = []
        c = InternalClient(retries=2, sleep=slept.append)
        tok = CancellationToken()
        tok.cancel()
        with pytest.raises(LegCancelled):
            c._request("GET", self.DEAD_URL, token=tok)
        assert slept == []

    def test_fault_plan_drop_surfaces_as_node_down(self):
        plan = FaultPlan(seed=1).drop("nodeX")
        slept = []
        c = InternalClient(retries=1, backoff=0.0, sleep=slept.append,
                           fault_plan=plan)
        with pytest.raises(NodeDownError):
            c._request("GET", self.DEAD_URL, node_id="nodeX")
        # both attempts consulted the plan (drop, retry, drop again)
        assert [e[2] for e in plan.events] == ["drop", "drop"]
        assert len(slept) == 1


class TestAssign:
    def _ex(self):
        # _assign is pure placement math over its arguments
        return ClusterExecutor.__new__(ClusterExecutor)

    def test_rank_beyond_owners_raises_not_clamps(self):
        ex = self._ex()
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        by0 = ex._assign(snap, "i", [0, 1, 2], set(), replica_rank=0)
        by1 = ex._assign(snap, "i", [0, 1, 2], set(), replica_rank=1)
        for s in (0, 1, 2):
            r0 = next(n for n, ss in by0.items() if s in ss)
            r1 = next(n for n, ss in by1.items() if s in ss)
            assert r0 != r1  # ranks are distinct owners, never clamped
        with pytest.raises(NodeDownError, match="no live replica"):
            ex._assign(snap, "i", [0], set(), replica_rank=2)

    def test_dead_filter_never_falls_back_to_racing_owner(self):
        ex = self._ex()
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        owners = [n.id for n in snap.shard_nodes("i", 0)]
        # rank 1 with the rank-1 owner dead: the old clamp would hand the
        # shard back to owners[0] — the node a hedge would be racing
        with pytest.raises(NodeDownError):
            ex._assign(snap, "i", [0], {owners[1]}, replica_rank=1)

    def test_on_exhausted_skip_drops_the_shard(self):
        ex = self._ex()
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        assert ex._assign(snap, "i", [0], set(), replica_rank=2,
                          on_exhausted="skip") == {}

    def test_all_owners_dead_raises(self):
        ex = self._ex()
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        owners = {n.id for n in snap.shard_nodes("i", 0)}
        with pytest.raises(NodeDownError):
            ex._assign(snap, "i", [0], owners)


def _park(token, then=None):
    """A remote leg that blocks until cancelled (a straggler)."""
    if token.wait(10.0):
        raise LegCancelled("parked leg cancelled")
    raise AssertionError("parked leg was never cancelled")


class TestRunLegs:
    def _res(self, reg, **kw):
        kw.setdefault("hedge_min_ms", 1.0)
        kw.setdefault("hedge_max_ms", 1.0)
        return Resilience(registry=reg, **kw)

    def test_hedge_wins_over_parked_primary(self):
        reg = MetricsRegistry()
        res = self._res(reg)
        racing = []

        def run_remote(node, shards, token):
            if node == "A":
                _park(token)
            return ("part", node, tuple(shards))

        def next_owners(shards, racing_node):
            racing.append(racing_node)
            return {"b": list(shards)}

        parts, failed = res.run_legs(
            {"a": [1, 2]}, {"a": "A", "b": "B"}, run_remote, next_owners)
        assert parts == [("part", "B", (1, 2))]
        assert failed == []
        assert racing == ["a"]
        assert reg.value(M.METRIC_CLUSTER_HEDGES) == 1.0
        assert reg.value(M.METRIC_CLUSTER_HEDGE_WINS) == 1.0

    def test_primary_wins_after_hedge_wave_breaks(self):
        reg = MetricsRegistry()
        res = self._res(reg)
        marks = []

        def run_remote(node, shards, token):
            if node == "B":
                raise NodeDownError("replica down")
            token.wait(0.03)  # slow but healthy primary
            return "pa"

        parts, failed = res.run_legs(
            {"a": [1]}, {"a": "A", "b": "B"}, run_remote,
            lambda s, r: {"b": list(s)},
            mark_failed=lambda n, t: marks.append((n, t)))
        assert parts == ["pa"]
        assert failed == []
        assert reg.value(M.METRIC_CLUSTER_HEDGES) == 1.0
        assert reg.value(M.METRIC_CLUSTER_HEDGE_WINS) == 0.0
        assert ("b", True) in marks

    def test_hedge_onto_racing_node_is_a_bug_not_a_retry(self):
        reg = MetricsRegistry()
        res = self._res(reg)
        with pytest.raises(AssertionError, match="racing node"):
            res.run_legs({"a": [1]}, {"a": "A"},
                         lambda n, s, t: _park(t),
                         lambda s, r: {"a": list(s)})

    def test_no_replica_to_hedge_onto_is_quietly_skipped(self):
        reg = MetricsRegistry()
        res = self._res(reg)

        def run_remote(node, shards, token):
            token.wait(0.03)
            return "pa"

        def next_owners(shards, racing):
            raise NodeDownError("no live replica")

        parts, failed = res.run_legs({"a": [1]}, {"a": "A"}, run_remote,
                                     next_owners)
        assert parts == ["pa"] and failed == []
        assert reg.value(M.METRIC_CLUSTER_HEDGES) == 0.0

    def test_timeout_reaps_stuck_leg(self):
        reg = MetricsRegistry()
        res = Resilience(registry=reg, hedge=False,
                         timeout_min_ms=20.0, timeout_max_ms=20.0)
        marks = []
        parts, failed = res.run_legs(
            {"a": [3]}, {"a": "A"}, lambda n, s, t: _park(t),
            lambda s, r: {}, mark_failed=lambda n, t: marks.append((n, t)))
        assert parts == []
        assert failed == [3]  # shard re-enters the executor failover loop
        assert marks == [("a", False)]  # timeout is not a transport error
        assert reg.value(M.METRIC_CLUSTER_LEG_TIMEOUTS, node="a") == 1.0

    def test_primary_failure_without_hedge_fails_the_group(self):
        reg = MetricsRegistry()
        res = Resilience(registry=reg, hedge=False)
        marks = []

        def run_remote(node, shards, token):
            raise NodeDownError("down")

        parts, failed = res.run_legs(
            {"a": [4, 5]}, {"a": "A"}, run_remote, lambda s, r: {},
            mark_failed=lambda n, t: marks.append((n, t)))
        assert parts == [] and sorted(failed) == [4, 5]
        assert marks == [("a", True)]
        assert res.breaker.state("a") == BREAKER_CLOSED  # 1 < threshold 3

    def test_local_leg_runs_first_and_merges(self):
        reg = MetricsRegistry()
        res = Resilience(registry=reg, hedge=False)
        parts, failed = res.run_legs(
            {"a": [1]}, {"a": "A"}, lambda n, s, t: "ra", lambda s, r: {},
            local_fn=lambda: "local")
        assert parts == ["local", "ra"] and failed == []

    def test_success_feeds_latency_tracker_and_breaker(self):
        reg = MetricsRegistry()
        res = Resilience(registry=reg, hedge=False)
        res.run_legs({"a": [1]}, {"a": "A"}, lambda n, s, t: "ra",
                     lambda s, r: {})
        assert res.tracker.percentile("a", 99.0) is not None
        assert res.breaker.state("a") == BREAKER_CLOSED
        # leg latency histogram observed under outcome=ok kind=primary
        h = reg.histogram(M.METRIC_CLUSTER_LEG_LATENCY,
                          outcome="ok", kind="primary")
        assert h is not None and h["count"] == 1


class TestAdaptivePolicies:
    def test_leg_timeout_tracks_p99_with_clamps(self):
        res = Resilience(timeout_factor=4.0, timeout_min_ms=50.0,
                         timeout_max_ms=30000.0)
        assert res.leg_timeout_s("a") == 30.0  # no samples: max
        for _ in range(10):
            res.tracker.observe("a", 0.001)
        assert res.leg_timeout_s("a") == 0.05  # 4 x 1ms clamps up to min
        for _ in range(64):
            res.tracker.observe("a", 100.0)
        assert res.leg_timeout_s("a") == 30.0  # 400s clamps down to max

    def test_leg_timeout_respects_deadline_budget(self):
        clk = ManualClock()
        res = Resilience()
        with deadline_scope(Deadline(clk.now() + 2.0, now=clk.now)):
            assert res.leg_timeout_s("a") == 2.0
            clk.advance(1.5)
            assert res.leg_timeout_s("a") == pytest.approx(0.5)
            clk.advance(1.0)
            assert res.leg_timeout_s("a") == 0.0  # budget exhausted
        assert res.leg_timeout_s("a") == 30.0  # scope cleared

    def test_hedge_delay_clamps_to_bounds(self):
        res = Resilience(hedge_min_ms=10.0, hedge_max_ms=100.0)
        assert res.hedge_delay_s("a") == 0.01  # no samples: min
        for _ in range(10):
            res.tracker.observe("a", 50.0)
        assert res.hedge_delay_s("a") == 0.1  # p95 clamps down to max

    def test_vetoed_routes_open_breakers_to_replicas(self):
        res = Resilience(breaker_threshold=1)
        res.breaker.record_failure("b")
        assert res.vetoed(["a", "b", "c"]) == {"b"}


class TestConfig:
    def test_toml_section_round_trips(self, tmp_path):
        p = tmp_path / "pilosa.toml"
        p.write_text(
            "[cluster.resilience]\n"
            "enabled = true\n"
            "hedge-percentile = 90.0\n"
            "breaker-threshold = 5\n"
            "timeout-min-ms = 10.0\n")
        cfg = Config.from_sources(toml_path=str(p), env={})
        assert cfg.cluster_resilience_enabled is True
        assert cfg.cluster_resilience_hedge_percentile == 90.0
        assert cfg.cluster_resilience_breaker_threshold == 5
        assert cfg.cluster_resilience_timeout_min_ms == 10.0
        res = Resilience.from_config(cfg)
        assert res.hedge_percentile == 90.0
        assert res.breaker.threshold == 5
        assert res.timeout_min_s == 0.01

    def test_env_override(self):
        cfg = Config.from_sources(
            env={"PILOSA_TPU_CLUSTER_RESILIENCE_HEDGE_MIN_MS": "7.5",
                 "PILOSA_TPU_CLUSTER_RESILIENCE_HEDGE": "false"})
        assert cfg.cluster_resilience_hedge_min_ms == 7.5
        res = Resilience.from_config(cfg)
        assert res.hedge_min_s == pytest.approx(0.0075)
        assert res.hedge is False

    def test_overrides_beat_config(self):
        res = Resilience.from_config(Config(), breaker_threshold=1)
        assert res.breaker.threshold == 1


def _fill(target, index):
    """Same dataset through any node/API surface (mirrors test_cluster)."""
    target.create_index(index)
    target.create_field(index, "f")
    rows, cols = [], []
    for c in range(0, 5 * SHARD_WIDTH, SHARD_WIDTH // 4):
        rows.append((c // 100) % 3)
        cols.append(c)
    target.import_bits(index, "f", rows=rows, cols=cols)
    return index


def _remote_primary(co, index):
    """A non-coordinator node owning rank-0 shards of `index` from the
    coordinator's current assignment."""
    ex = co.executor
    snap = ex._snapshot_fn()
    by_node = ex._assign(snap, index, sorted(ex._shards_fn(index)), set())
    return next(nid for nid in by_node if nid != ex.node_id)


class TestClusterFaultInjection:
    """End-to-end over LocalCluster + FaultPlan: real HTTP legs, seeded
    faults at the client boundary, results checked against a no-fault
    single-node oracle."""

    def test_all_local_fanout_uses_no_thread_pool(self, monkeypatch):
        c = LocalCluster(1)
        try:
            _fill(c.coordinator, "rl")
            want = c.coordinator.query("rl", "Count(Row(f=0))")

            def boom(*a, **kw):
                raise AssertionError("pool created for all-local fan-out")

            monkeypatch.setattr(
                "pilosa_tpu.cluster.executor.ThreadPoolExecutor", boom)
            assert c.coordinator.query("rl", "Count(Row(f=0))") == want
            c.coordinator.query("rl", f"Set({7 * SHARD_WIDTH}, f=1)")
            assert c.coordinator.query("rl", "Count(Row(f=1))") != want
        finally:
            c.close()

    @pytest.fixture()
    def faulty_cluster(self):
        plan = FaultPlan()  # seed from PILOSA_TPU_FAULT_SEED (tier1.sh lane)
        c = LocalCluster(3, replica_n=2, fault_plan=plan)
        try:
            yield c, plan
        finally:
            c.close()

    def test_hedged_straggler_matches_no_fault_oracle(self, faulty_cluster):
        c, plan = faulty_cluster
        oracle = API()
        _fill(oracle, "hs")
        _fill(c.coordinator, "hs")
        q = "Count(Row(f=0))"
        want = oracle.query("hs", q)

        co = c.coordinator
        reg = MetricsRegistry()
        # huge breaker threshold isolates hedging from breaker routing
        res = co.enable_resilience(registry=reg, hedge_min_ms=1.0,
                                   breaker_threshold=1 << 30)
        try:
            for _ in range(3):  # warm the latency windows, fault-free
                assert co.query("hs", q) == want
            victim = _remote_primary(co, "hs")
            plan.delay(victim, 2.0)
            t0 = time.monotonic()
            got = co.query("hs", q)
            elapsed = time.monotonic() - t0
            plan.clear()
            assert got == want  # bit-identical despite the straggler
            assert elapsed < 1.6  # hedge beat the 2s injected delay
            assert sum(v for k, v in reg.as_json()["counters"].items()
                       if M.METRIC_CLUSTER_HEDGES in str(k)) >= 1 \
                or reg.value(M.METRIC_CLUSTER_HEDGES) >= 1.0
            assert reg.value(M.METRIC_CLUSTER_HEDGE_WINS) >= 1.0
            text = reg.prometheus_text()
            assert "cluster_hedges_total" in text
            assert "cluster_leg_latency_ms_bucket" in text
        finally:
            plan.clear()
            co.disable_resilience()

    def test_writes_never_enter_the_hedged_path(self, faulty_cluster):
        c, plan = faulty_cluster
        co = c.coordinator
        _fill(co, "wh")
        res = co.enable_resilience(hedge_min_ms=1.0)
        calls = []
        orig = res.run_legs

        def spy(remote, nodes, run_remote, next_owners, **kw):
            calls.append(kw.get("hedgeable"))
            return orig(remote, nodes, run_remote, next_owners, **kw)

        res.run_legs = spy
        try:
            co.query("wh", f"Set({9 * SHARD_WIDTH + 5}, f=2)")
            assert calls == []  # the write mirror path bypasses run_legs
            co.query("wh", "Count(Row(f=2))")
            assert calls and all(h is True for h in calls)
        finally:
            co.disable_resilience()

    def test_flap_recovers_within_client_retries(self, faulty_cluster):
        # the flapping node fails attempt 1 and recovers before attempt 2:
        # the client's jittered retry absorbs it — no failover, no
        # membership change, answer identical to the no-fault oracle
        c, plan = faulty_cluster
        oracle = API()
        _fill(oracle, "fr")
        _fill(c.coordinator, "fr")
        q = "Count(Row(f=0))"
        want = oracle.query("fr", q)
        co = c.coordinator
        assert co.query("fr", q) == want  # warm, fault-free
        victim = _remote_primary(co, "fr")
        downs = []
        orig_down = co.executor._on_node_down
        co.executor._on_node_down = lambda nid: (downs.append(nid),
                                                 orig_down(nid))
        try:
            plan.drop(victim, first=plan.seen(victim), count=1)
            assert co.query("fr", q) == want
            assert downs == []  # absorbed inside the client retry loop
        finally:
            co.executor._on_node_down = orig_down
            plan.clear()

    def test_failover_then_breaker_recovery(self):
        # retries=0 clients: a drop surfaces immediately as NodeDownError,
        # the leg fails over to the replica (answer still matches the
        # oracle), the breaker opens, and after open_ms a half-open probe
        # closes it again — firing on_node_up back into membership
        plan = FaultPlan()
        c = LocalCluster(
            3, replica_n=2,
            client_factory=lambda i: InternalClient(retries=0,
                                                    fault_plan=plan))
        try:
            oracle = API()
            _fill(oracle, "fo")
            _fill(c.coordinator, "fo")
            q = "Count(Row(f=0))"
            want = oracle.query("fo", q)
            co = c.coordinator
            transitions = []
            reg = MetricsRegistry()
            res = co.enable_resilience(
                registry=reg, hedge=False, breaker_threshold=1,
                breaker_open_ms=100.0,
                on_breaker_transition=lambda n, f, t: transitions.append(
                    (n, f, t)))
            try:
                assert co.query("fo", q) == want  # warm, fault-free
                victim = _remote_primary(co, "fo")
                downs = []
                orig_down = co.executor._on_node_down
                co.executor._on_node_down = lambda nid: (
                    downs.append(nid), orig_down(nid))
                plan.drop(victim, first=plan.seen(victim), count=1)
                assert co.query("fo", q) == want  # replica failover
                co.executor._on_node_down = orig_down
                assert downs == [victim]
                assert res.breaker.state(victim) == BREAKER_OPEN
                assert reg.value(M.METRIC_CLUSTER_BREAKER_STATE,
                                 node=victim) == 2.0
                # heartbeat sees the node again (the drop was injected;
                # the server never actually died)
                c.disco.up(victim)
                time.sleep(0.15)  # breaker_open_ms elapses
                assert co.query("fo", q) == want  # the half-open probe
                assert res.breaker.state(victim) == BREAKER_CLOSED
                assert [(f, t) for n, f, t in transitions
                        if n == victim] == [
                    (BREAKER_CLOSED, BREAKER_OPEN),
                    (BREAKER_OPEN, BREAKER_HALF_OPEN),
                    (BREAKER_HALF_OPEN, BREAKER_CLOSED),
                ]
                assert c.disco.is_live(victim)  # on_node_up rejoined it
            finally:
                co.disable_resilience()
        finally:
            plan.clear()
            c.close()
