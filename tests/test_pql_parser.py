"""PQL parser tests (reference: pql/pql_test.go behaviors)."""

import pytest

from pilosa_tpu.pql import parse
from pilosa_tpu.pql.ast import Condition
from pilosa_tpu.pql.parser import ParseError


def one(src):
    q = parse(src)
    assert len(q.calls) == 1
    return q.calls[0]


def test_simple_row():
    c = one("Row(f=1)")
    assert c.name == "Row" and c.args == {"f": 1}


def test_multiple_calls():
    q = parse("Set(1, f=1)Set(2, f=2)")
    assert [c.name for c in q.calls] == ["Set", "Set"]
    assert q.calls[0].args == {"_col": 1, "f": 1}


def test_nested_calls():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert c.name == "Count"
    inner = c.children[0]
    assert inner.name == "Intersect"
    assert [ch.args for ch in inner.children] == [{"a": 1}, {"b": 2}]


def test_strings_and_escapes():
    c = one('Row(f="it\\"s")')
    assert c.args == {"f": 'it"s'}
    c = one("Row(f='single')")
    assert c.args == {"f": "single"}


def test_conditions():
    c = one("Row(n > 5)")
    assert c.args["n"] == Condition(">", 5)
    c = one("Row(n <= -3)")
    assert c.args["n"] == Condition("<=", -3)
    c = one("Row(3 < n < 7)")
    assert c.args["n"] == Condition("between", [4, 6])
    c = one("Row(3 <= n <= 7)")
    assert c.args["n"] == Condition("between", [3, 7])
    c = one("Row(n != null)")
    assert c.args["n"] == Condition("!=", None)


def test_positional_field():
    c = one("TopN(myfield, n=5)")
    assert c.args == {"_field": "myfield", "n": 5}


def test_timestamp_positional():
    c = one("Set(2, f=1, 2010-01-02T03:04)")
    assert c.args["_col"] == 2
    assert c.args["f"] == 1
    assert c.args["_timestamp"] == "2010-01-02T03:04"


def test_from_to_strings():
    c = one("Row(f=1, from='2010-01-01T00:00', to='2011-01-01T00:00')")
    assert c.args["from"] == "2010-01-01T00:00"


def test_lists_and_bools():
    c = one("ConstRow(columns=[1, 2, 'x'])")
    assert c.args["columns"] == [1, 2, "x"]
    c = one("Set(1, b=true)")
    assert c.args["b"] is True


def test_named_call_arg():
    c = one("GroupBy(Rows(a), aggregate=Sum(field=v), limit=10)")
    assert c.children[0].name == "Rows"
    assert c.args["aggregate"].name == "Sum"
    assert c.args["limit"] == 10


def test_floats_negative():
    c = one("Row(price > 1.5)")
    assert c.args["price"] == Condition(">", 1.5)
    c = one("Set(1, n=-42)")
    assert c.args["n"] == -42


def test_trailing_comma():
    c = one("Row(f=1,)")
    assert c.args == {"f": 1}


@pytest.mark.parametrize("bad", [
    "Row(f=", "row(f=1)", "Row(f=1))", "Row(@)", "Row(f==)",
])
def test_parse_errors(bad):
    with pytest.raises(ParseError):
        parse(bad)


def test_repr_roundtrip_shape():
    c = one("GroupBy(Rows(a), Rows(b), limit=2)")
    assert "GroupBy" in repr(c) and "Rows" in repr(c)
