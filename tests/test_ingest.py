"""Ingest kit tests (reference patterns: batch/batch_test.go,
idk ingest tests, idalloc tests)."""

import os

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.core.schema import FieldOptions, FieldType
from pilosa_tpu.ingest import (Batch, CSVSource, IDAllocator, Ingester,
                               ListSource)


@pytest.fixture()
def api():
    a = API()
    a.create_index("i")
    idx = a.holder.index("i")
    idx.create_field("color", FieldOptions(type=FieldType.SET, keys=True))
    idx.create_field("size", FieldOptions(type=FieldType.MUTEX, keys=True))
    idx.create_field("age", FieldOptions(type=FieldType.INT))
    idx.create_field("active", FieldOptions(type=FieldType.BOOL))
    return a


def count(api, pql):
    return api.query("i", pql)[0]


def test_batch_basic(api):
    b = Batch(api, "i", size=3)
    flushed = b.add({"id": 1, "color": ["red", "blue"], "age": 10})
    assert not flushed
    b.add({"id": 2, "color": ["red"], "size": "L", "active": True})
    flushed = b.add({"id": 1 << 20, "age": -5})  # second shard
    assert flushed  # auto-flush at size
    assert b.imported == 3 and len(b) == 0
    assert count(api, "Count(Row(color=red))") == 2
    assert count(api, "Count(Row(color=blue))") == 1
    assert api.query("i", "Sum(field=age)")[0].val == 5
    assert count(api, "Count(Row(active=true))") == 1
    assert count(api, "Count(All())") == 3


def test_batch_mutex_scalar(api):
    b = Batch(api, "i", size=10)
    b.add({"id": 7, "size": "S"})
    b.flush()
    b.add({"id": 7, "size": "M"})  # mutex overwrite
    b.flush()
    assert count(api, "Count(Row(size=M))") == 1
    assert count(api, "Count(Row(size=S))") == 0


def test_batch_keyed_index():
    api = API()
    api.create_index("k", {"keys": True})
    api.holder.index("k").create_field(
        "color", FieldOptions(type=FieldType.SET, keys=True))
    b = Batch(api, "k", size=10)
    b.add({"id": "userA", "color": ["red"]})
    b.add({"id": "userB", "color": ["red"]})
    b.flush()
    r = api.query("k", "Row(color=red)")[0]
    assert sorted(r.keys) == ["userA", "userB"]


def test_idalloc_sessions(tmp_path):
    path = str(tmp_path / "ids.journal")
    a = IDAllocator(path)
    r1 = a.reserve("s1", 100, offset=0)
    assert (r1.base, r1.count) == (1, 100)
    # same session+offset replays the same range (crash retry)
    again = a.reserve("s1", 100, offset=0)
    assert again.base == r1.base
    r2 = a.reserve("s2", 10, offset=0)
    assert r2.base == r1.end
    a.commit("s1")
    # reload from journal: next id preserved
    b = IDAllocator(path)
    r3 = b.reserve("s3", 5, offset=0)
    assert r3.base >= r2.end


def test_idalloc_commit_returns_tail():
    a = IDAllocator()
    r = a.reserve("s", 1000)
    a.commit("s", count=10)  # only used 10
    r2 = a.reserve("t", 5)
    assert r2.base == r.base + 10


def test_idalloc_commit_tail_survives_reload(tmp_path):
    # The tail rollback must be journaled, not memory-only.
    path = str(tmp_path / "ids.jsonl")
    a = IDAllocator(path)
    r = a.reserve("s", 1000)
    a.commit("s", count=10)
    b = IDAllocator(path)
    assert b.next_id == a.next_id == r.base + 10
    assert b.reserve("t", 5).base == r.base + 10


def test_idalloc_reserve_then_crash_replays_range(tmp_path):
    # Crash between reserve and commit: the journal already has the
    # reservation, so the retry (same session, same stream offset) must
    # get the SAME range back — the idempotence the streaming pipeline's
    # auto-id path leans on (stream/pipeline.py session naming).
    path = str(tmp_path / "ids.jsonl")
    a = IDAllocator(path)
    r = a.reserve("g:t:0:0", 400, offset=0)
    b = IDAllocator(path)  # crash: no commit ever journaled
    again = b.reserve("g:t:0:0", 400, offset=0)
    assert (again.base, again.count) == (r.base, r.count)
    # a LATER stream position is a new reservation, past the first
    nxt = b.reserve("g:t:0:400", 400, offset=1)
    assert nxt.base >= r.end


def test_idalloc_commit_then_crash_keeps_next_id(tmp_path):
    # Crash after commit: the committed tail rollback is journaled, so
    # the reloaded allocator neither reuses nor leaks the tail.
    path = str(tmp_path / "ids.jsonl")
    a = IDAllocator(path)
    r = a.reserve("s", 1000, offset=0)
    a.commit("s", count=250)
    b = IDAllocator(path)
    assert b.next_id == r.base + 250
    assert b.reserve("t", 5, offset=0).base == r.base + 250


def test_idalloc_interleaved_sessions_replay(tmp_path):
    # Two live sessions interleaving reserves/commits; a crash replays
    # the journal into the same allocation state.
    path = str(tmp_path / "ids.jsonl")
    a = IDAllocator(path)
    r1 = a.reserve("s1", 100, offset=0)
    r2 = a.reserve("s2", 50, offset=0)
    assert r2.base == r1.end
    a.commit("s2")  # commits in a different order than reserves
    r3 = a.reserve("s1", 100, offset=1)  # s1 advances to its next batch
    a.commit("s1")
    b = IDAllocator(path)
    assert b.next_id == a.next_id
    # fresh work lands past everything either session touched
    assert b.reserve("s3", 5, offset=0).base >= r3.end


def test_csv_source_typed_header(api, tmp_path):
    p = tmp_path / "data.csv"
    p.write_text(
        "id,name__S,age__I,tags__SS,ok__B,price__F2\n"
        "1,alice,30,a;b,true,9.99\n"
        "2,bob,40,b,false,1.50\n"
        "3,carol,,c;d,true,\n")
    src = CSVSource(str(p))
    ing = Ingester(api, "csvidx", src, batch_size=2)
    assert ing.run() == 3
    a = api
    assert a.query("csvidx", "Count(Row(tags=b))")[0] == 2
    assert a.query("csvidx", "Sum(field=age)")[0].val == 70
    assert a.query("csvidx", "Count(Row(name=carol))")[0] == 1
    # decimal scale applied
    assert abs(a.query("csvidx", "Max(field=price)")[0].val - 9.99) < 1e-9


def test_ingester_auto_id(api):
    schema = [("color", FieldOptions(type=FieldType.SET, keys=True))]
    src = ListSource(schema, [{"color": ["x"]}, {"color": ["x", "y"]}],
                     id_col=None)
    ing = Ingester(api, "autoidx", src, batch_size=10)
    assert ing.run() == 2
    assert api.query("autoidx", "Count(Row(color=x))")[0] == 2
    r = api.query("autoidx", "Row(color=y)")[0]
    assert len(r.columns) == 1


def test_ingester_schema_inference(api):
    src = CSVSource("id,city__S,pop__I\n9,nyc,8000000\n", inline=True)
    Ingester(api, "inferidx", src).run()
    idx = api.holder.index("inferidx")
    assert idx.field("city").options.keys
    assert idx.field("pop").options.type == FieldType.INT
    assert api.query("inferidx", "Count(Row(city=nyc))")[0] == 1


def test_kafka_source_gated_and_fake(api):
    # gated: no kafka client in the image
    from pilosa_tpu.ingest.kafka import KafkaSource

    class FakeConsumer:
        def __iter__(self):
            import json as j

            class M:
                def __init__(self, v):
                    self.value = v
            for v in [{"id": 1, "color": ["red"]}, {"id": 2, "color": ["blue"]}]:
                yield M(j.dumps(v))

    class FakeClient:
        def KafkaConsumer(self, *a, **k):
            return FakeConsumer()

    src = KafkaSource("localhost:9092", ["t"], "g",
                      fields=["id", "color__SS"], client=FakeClient())
    ing = Ingester(api, "kafkaidx", src)
    assert ing.run() == 2
    assert api.query("kafkaidx", "Count(Row(color=red))")[0] == 1


# -- columnar fast-path regressions (round-5 review findings) --------------

def test_csv_columnar_trailing_semicolons(api):
    src = CSVSource("id,tags__IS\n1,5;6;\n2,;7\n3,\n", inline=True)
    assert Ingester(api, "semi", src).run() == 3
    assert api.query("semi", "Count(Row(tags=5))")[0] == 1
    assert api.query("semi", "Count(Row(tags=6))")[0] == 1
    assert api.query("semi", "Count(Row(tags=7))")[0] == 1


def test_csv_columnar_ragged_rows_not_misaligned(api):
    # one short row + one long row cancel out in total cell count; the
    # fast path must NOT shift later columns (falls back to csv.reader,
    # which localizes the damage to the ragged rows)
    text = "id,a__I,b__I\n1,10,20\n2,30\n3,40,50,60\n4,70,80\n"
    src = CSVSource(text, inline=True)
    n = Ingester(api, "rag", src).run()
    assert n == 4
    # well-formed rows land in the right fields
    assert api.query("rag", "Count(Row(a=10))")[0] == 1
    assert api.query("rag", "Count(Row(b=80))")[0] == 1
    # nothing from row 3's overflow cell lands in b as 40/50 shifted junk
    assert api.query("rag", "Count(Row(b=40))")[0] == 0


def test_csv_columnar_bool_whitespace(api):
    src = CSVSource("id,ok__B\n1, true\n2,false \n3,TRUE\n", inline=True)
    assert Ingester(api, "bw", src).run() == 3
    assert api.query("bw", "Count(Row(ok=1))")[0] == 2
    assert api.query("bw", "Count(Row(ok=0))")[0] == 1


def test_csv_columnar_matches_per_record_path(api):
    # same file through columns() and records() must build identical data
    text = ("id,city__IS,dev__ID,age__I,name__S\n"
            + "\n".join(f"{i},{i % 7},{i % 3},{i * 2},{'u%d' % (i % 5)}"
                        for i in range(500)) + "\n")
    a1, a2 = API(), API()
    assert Ingester(a1, "x", CSVSource(text, inline=True)).run() == 500

    src2 = CSVSource(text, inline=True)
    ing2 = Ingester(a2, "x", src2, batch_size=64)
    # force the per-record path by hiding .columns behind a plain facade
    ing2.source = type("S", (), {
        "schema": src2.schema, "records": src2.records,
        "id_column": src2.id_column})()
    assert ing2.run() == 500
    for q in ("Count(Row(city=3))", "Count(Row(dev=1))",
              "Count(Row(name=u2))", "Count(Row(age > 500))"):
        assert a1.query("x", q)[0] == a2.query("x", q)[0], q
