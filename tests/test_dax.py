"""DAX control-plane tests (reference: dax/test/dax.go harness and the
controller/computer/queryer behaviors of dax/).

The VERDICT r3 #4 done-criterion drives the shape: kill a compute node
in the harness, shards get reassigned, and the query returns COMPLETE
results (rebuilt from the shared writelog/snapshots)."""

import numpy as np
import pytest

from pilosa_tpu.dax.directive import Directive
from pilosa_tpu.dax.harness import DaxCluster
from pilosa_tpu.dax.storage import Snapshotter, WriteLogger
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def dax(tmp_path):
    c = DaxCluster(3, shared_dir=str(tmp_path), snapshot_every=8)
    yield c
    c.close()


def _fill(dax, index="t", rows=3, per_shard=40, shards=4):
    dax.controller.create_table(index, {}, [
        {"name": "f", "options": {"type": "set"}},
        {"name": "n", "options": {"type": "int"}},
    ])
    rng = np.random.default_rng(5)
    oracle = {r: set() for r in range(rows)}
    vals = {}
    for s in range(shards):
        rs, cs = [], []
        for _ in range(per_shard):
            r = int(rng.integers(0, rows))
            c = s * SHARD_WIDTH + int(rng.integers(0, SHARD_WIDTH))
            rs.append(r)
            cs.append(c)
            oracle[r].add(c)
        dax.queryer.import_bits(index, "f", rows=rs, cols=cs)
        vcols = [s * SHARD_WIDTH + i for i in range(10)]
        vvals = [int(rng.integers(-50, 50)) for _ in vcols]
        dax.queryer.import_values(index, "n", cols=vcols, values=vvals)
        for c, v in zip(vcols, vvals):
            vals[c] = v
    return oracle, vals


class TestDaxBasics:
    def test_queries_match_oracle(self, dax):
        oracle, vals = _fill(dax)
        for r, cols in oracle.items():
            assert dax.queryer.query("t", f"Count(Row(f={r}))")[0] == len(cols)
        assert dax.queryer.query("t", "Sum(field=n)")[0].val == \
            sum(vals.values())

    def test_shards_spread_across_computers(self, dax):
        _fill(dax)
        owners = {nid for (t, s), nid in dax.controller.assignment().items()}
        assert len(owners) >= 2, "balancer left everything on one node"
        # each computer holds only its assigned shards
        for comp in dax.computers:
            local = comp.api.holder.indexes["t"].shards()
            assigned = {s for (t, s) in comp.assigned if t == "t"}
            assert local <= assigned | {0}

    def test_writes_are_logged_before_apply(self, dax, tmp_path):
        _fill(dax)
        wl = WriteLogger(str(tmp_path))
        assert wl.shards("t"), "writelog is empty"
        total_ops = sum(wl.length("t", s) for s in wl.shards("t"))
        assert total_ops > 0

    def test_directive_version_regression_rejected(self, dax):
        _fill(dax)
        comp = dax.computers[0]
        v = comp.directive_version
        stale = Directive(version=v - 1, schema=[], assigned=[])
        out = comp.apply_directive(stale.to_json())
        assert not out["applied"]
        assert comp.directive_version == v


class TestDaxFailover:
    def test_kill_computer_reassigns_and_data_survives(self, dax):
        """The headline behavior: kill a node; shards reassign; queries
        return complete results rebuilt from writelog + snapshots."""
        oracle, vals = _fill(dax)
        before = {r: dax.queryer.query("t", f"Count(Row(f={r}))")[0]
                  for r in oracle}
        # kill the busiest computer
        counts = {}
        for (t, s), nid in dax.controller.assignment().items():
            counts[nid] = counts.get(nid, 0) + 1
        victim = max(counts, key=counts.get)
        vi = next(i for i, c in enumerate(dax.computers)
                  if c.node.id == victim)
        dax.kill(vi)
        # every shard has a live owner now
        for key, nid in dax.controller.assignment().items():
            assert nid != victim
        after = {r: dax.queryer.query("t", f"Count(Row(f={r}))")[0]
                 for r in oracle}
        assert after == before, "data lost in failover"
        assert dax.queryer.query("t", "Sum(field=n)")[0].val == \
            sum(vals.values())
        # and writes keep working post-failover
        newcol = 7 * SHARD_WIDTH + 1
        dax.queryer.query("t", f"Set({newcol}, f=0)")
        assert dax.queryer.query("t", "Count(Row(f=0))")[0] == \
            before[0] + 1

    def test_poller_detects_silent_death(self, dax):
        oracle, _ = _fill(dax)
        victim = dax.computers[1].node.id
        dax.silence(1)
        # poller hasn't run: node still considered live
        assert victim in dax.controller.live_ids()
        # the victim stops checking in; the others keep heartbeating
        dax.controller.last_seen[victim] -= 3600
        for comp in dax.computers:
            if comp.node.id != victim:
                dax.controller.checkin(comp.node.id)
        newly = dax.controller.poll()
        assert victim in newly
        assert victim not in dax.controller.live_ids()
        for r, cols in oracle.items():
            assert dax.queryer.query("t", f"Count(Row(f={r}))")[0] == len(cols)

    def test_snapshot_compaction_and_resume(self, dax, tmp_path):
        """Past the op threshold a shard snapshots; a new owner resumes
        from snapshot + tail replay, not a full log replay."""
        dax.controller.create_table("s", {}, [
            {"name": "f", "options": {"type": "set"}}])
        for k in range(20):  # snapshot_every=8 -> snapshots exist
            dax.queryer.query("s", f"Set({k}, f=1)")
        snap = Snapshotter(str(tmp_path))
        assert snap.latest("s", 0) is not None, "no snapshot written"
        version, arrays = snap.latest("s", 0)
        assert version >= 8
        owner = dax.controller.assignment()[("s", 0)]
        oi = next(i for i, c in enumerate(dax.computers)
                  if c.node.id == owner)
        dax.kill(oi)
        assert dax.queryer.query("s", "Count(Row(f=1))")[0] == 20

    def test_reset_directive_rebuilds_node(self, dax):
        oracle, _ = _fill(dax)
        comp = next(c for c in dax.computers
                    if any(t == "t" for t, s in c.assigned))
        d = Directive(version=comp.directive_version, method="reset",
                      schema=[dict(t) for t in dax.controller.schema],
                      assigned=sorted(comp.assigned))
        comp.apply_directive(d.to_json())
        for r, cols in oracle.items():
            assert dax.queryer.query("t", f"Count(Row(f={r}))")[0] == len(cols)


class TestDaxColdStart:
    def test_controller_recovers_shards_from_logs(self, tmp_path):
        c1 = DaxCluster(2, shared_dir=str(tmp_path))
        try:
            oracle, _ = _fill(c1)
        finally:
            c1.close()
        # a brand-new control plane + computers over the same shared dir
        c2 = DaxCluster(2, shared_dir=str(tmp_path))
        try:
            c2.controller.schema = [
                {"index": "t", "options": {}, "fields": [
                    {"name": "f", "options": {"type": "set"}},
                    {"name": "n", "options": {"type": "int"}}]}]
            c2.controller.recover_from_logs()
            for r, cols in oracle.items():
                assert c2.queryer.query("t", f"Count(Row(f={r}))")[0] == \
                    len(cols)
        finally:
            c2.close()
