"""Incremental device write-merge (VERDICT r1 #5).

A small write between two queries must advance the cached stacked tensor
with a tiny device scatter — NOT invalidate it and re-upload the whole
stack (SURVEY §7 "Mutability on device"; the reference's analog is RBF's
WAL absorbing writes between checkpoints, rbf/db.go:149-230).
"""

import numpy as np
import pytest

from pilosa_tpu.core import FieldOptions, FieldType, Holder
from pilosa_tpu.core.stacked import UPLOAD_STATS
from pilosa_tpu.pql import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def env():
    h = Holder()
    e = Executor(h)
    return h, e


def uploads():
    return UPLOAD_STATS["count"]


def fill(e, rows=4, shards=2, per_row=50, field="f"):
    rng = np.random.default_rng(9)
    oracle = {r: set() for r in range(rows)}
    for s in range(shards):
        for r in range(rows):
            for c in rng.integers(0, SHARD_WIDTH, per_row):
                col = s * SHARD_WIDTH + int(c)
                e.execute("i", f"Set({col}, {field}={r})")
                oracle[r].add(col)
    return oracle


class TestSetMerge:
    def test_setbit_between_queries_no_reupload(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        oracle = fill(e)
        e.execute("i", "Count(Row(f=0))")  # warm: build + upload
        base = uploads()
        # representable write: existing row, existing structure
        newcol = SHARD_WIDTH + 777
        assert newcol not in oracle[0]
        e.execute("i", f"Set({newcol}, f=0)")
        oracle[0].add(newcol)
        got = e.execute("i", "Count(Row(f=0))TopN(f, n=2)")
        assert got[0] == len(oracle[0])
        assert uploads() == base, "setbit caused a full stack re-upload"
        # repeated writes keep merging without uploads
        for k in range(5):
            e.execute("i", f"Clear({sorted(oracle[0])[k]}, f=0)")
            oracle[0].discard(sorted(oracle[0])[k])
        assert e.execute("i", "Count(Row(f=0))")[0] == len(oracle[0])
        assert uploads() == base

    def test_set_then_clear_same_bit_resolves_in_order(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        fill(e)
        e.execute("i", "Count(Row(f=1))")
        c = SHARD_WIDTH + 4242
        e.execute("i", f"Set({c}, f=1)")
        e.execute("i", f"Clear({c}, f=1)")
        assert c not in e.execute("i", "Row(f=1)")[0].columns
        e.execute("i", f"Clear({c}, f=1)")
        e.execute("i", f"Set({c}, f=1)")
        assert c in e.execute("i", "Row(f=1)")[0].columns

    def test_new_row_appends_without_reupload(self, env):
        """Streaming ingest of NEW rows advances the stack by appending a
        slot in place — no full re-upload (VERDICT r3 #5: the common
        ingest-while-querying pattern must benefit from the merge)."""
        h, e = env
        h.create_index("i").create_field("f")
        oracle = fill(e)
        e.execute("i", "Count(Row(f=0))")
        base = uploads()
        e.execute("i", "Set(5, f=99)")  # new row: appended slot
        top = e.execute("i", "TopN(f, n=10)")[0]
        assert (99, 1) in [(p.id, p.count) for p in top.pairs]
        assert uploads() == base, "new-row append caused a re-upload"
        for r, cols in oracle.items():
            assert e.execute("i", f"Count(Row(f={r}))")[0] == len(cols)
        # stream more new rows between queries; uploads stay flat
        for k in range(100, 110):
            e.execute("i", f"Set({k}, f={k})")
            assert e.execute("i", f"Count(Row(f={k}))")[0] == 1
        assert uploads() == base
        # and the merged state still matches a fresh rebuild exactly
        merged = {r: e.execute("i", f"Row(f={r})")[0].columns
                  for r in list(oracle) + [99]}
        for fld in h.index("i").fields.values():
            if hasattr(fld, "_stacked_cache"):
                fld._stacked_cache.clear()
        for r, cols in merged.items():
            assert e.execute("i", f"Row(f={r})")[0].columns == cols

    def test_merge_matches_fresh_rebuild(self, env):
        """Merged stack must equal a from-scratch build bit for bit."""
        h, e = env
        h.create_index("i").create_field("f")
        fill(e, rows=3, shards=3)
        e.execute("i", "Count(Row(f=0))")
        rng = np.random.default_rng(3)
        for _ in range(40):
            r = int(rng.integers(0, 3))
            c = int(rng.integers(0, 3 * SHARD_WIDTH))
            if rng.random() < 0.5:
                e.execute("i", f"Set({c}, f={r})")
            else:
                e.execute("i", f"Clear({c}, f={r})")
            e.execute("i", "Count(Row(f=0))")  # keep advancing the stack
        merged = [e.execute("i", f"Row(f={r})")[0].columns for r in range(3)]
        # fresh executor+holder state: drop caches, force full rebuild
        for fld in h.index("i").fields.values():
            if hasattr(fld, "_stacked_cache"):
                fld._stacked_cache.clear()
        fresh = [e.execute("i", f"Row(f={r})")[0].columns for r in range(3)]
        assert merged == fresh

    def test_mutex_write_merges(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("m", FieldOptions(type=FieldType.MUTEX))
        for col, row in [(1, 0), (2, 0), (3, 1)]:
            e.execute("i", f"Set({col}, m={row})")
        e.execute("i", "Count(Row(m=0))")
        base = uploads()
        e.execute("i", "Set(2, m=1)")  # moves col 2: clear row0 + set row1
        assert e.execute("i", "Row(m=0)")[0].columns == [1]
        assert sorted(e.execute("i", "Row(m=1)")[0].columns) == [2, 3]
        assert uploads() == base


class TestBSIMerge:
    def test_value_update_no_reupload(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        cols = list(range(0, 2000, 7))
        vals = {c: (c % 97) - 48 for c in cols}
        for fshard in (0, 1):
            f = idx.field("n")
            f.set_values([c + fshard * SHARD_WIDTH for c in cols],
                         list(vals.values()))
        assert e.execute("i", "Sum(field=n)")[0].val == 2 * sum(vals.values())
        base = uploads()
        f = idx.field("n")
        f.set_values([14], [40])  # update within existing depth
        want = 2 * sum(vals.values()) - vals[14] + 40
        assert e.execute("i", "Sum(field=n)")[0].val == want
        assert uploads() == base, "BSI value update caused re-upload"
        # sign flip + clear also merge
        f.set_values([21], [-5])
        want += -5 - vals[21]
        assert e.execute("i", "Sum(field=n)")[0].val == want
        f.clear_value(28)
        want -= vals[28]
        assert e.execute("i", "Sum(field=n)")[0].val == want
        assert uploads() == base

    def test_depth_growth_rebuilds_correctly(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        f = idx.field("n")
        f.set_values([1, 2, 3], [5, 6, 7])
        assert e.execute("i", "Sum(field=n)")[0].val == 18
        f.set_values([4], [1 << 40])  # depth growth: not representable
        assert e.execute("i", "Sum(field=n)")[0].val == 18 + (1 << 40)

    def test_range_after_merge(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        f = idx.field("n")
        f.set_values(list(range(10)), list(range(10)))
        assert e.execute("i", "Count(Row(n > 4))")[0] == 5
        f.set_values([2], [9])
        assert e.execute("i", "Count(Row(n > 4))")[0] == 6
        assert sorted(e.execute("i", "Row(n == 9)")[0].columns) == [2, 9]


class TestOverflow:
    def test_delta_overflow_falls_back(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        fill(e, rows=2, shards=1, per_row=30)
        e.execute("i", "Count(Row(f=0))")
        # blow past the per-fragment op cap without touching new rows
        frag = h.index("i").field("f").fragment(0)
        for c in range(600):
            frag.set_bit(0, 10_000 + c)
        assert e.execute("i", "Count(Row(f=0))")[0] > 600
        merged = e.execute("i", "Row(f=0)")[0].columns
        for fld in h.index("i").fields.values():
            if hasattr(fld, "_stacked_cache"):
                fld._stacked_cache.clear()
        assert e.execute("i", "Row(f=0)")[0].columns == merged

    def test_unlogged_version_bump_forces_rebuild(self, env):
        """restore/snapshot paths replace planes and bump version without
        logging; a later logged write must NOT let the log bridge across
        that gap (it would serve pre-restore data merged with one op)."""
        h, e = env
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        f = idx.field("n")
        f.set_values([1, 2], [10, 20])
        assert e.execute("i", "Sum(field=n)")[0].val == 30
        # external wholesale replacement (as api.restore_tar does)
        b = f.bsi_fragment(0)
        b.planes = np.zeros_like(b.planes)
        b.version += 1
        f.set_values([3], [5])  # logged write AFTER the unlogged bump
        assert e.execute("i", "Sum(field=n)")[0].val == 5

    def test_wide_bsi_ops_capped_by_replay_cost(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        f = idx.field("n")
        f.set_values(list(range(100)), [1] * 100)
        assert e.execute("i", "Sum(field=n)")[0].val == 100
        # wide repeated updates blow the cumulative replay budget -> the
        # log resets and queries stay correct via rebuild
        for k in range(5):
            f.set_values(list(range(2000)), [k] * 2000)
        assert e.execute("i", "Sum(field=n)")[0].val == 4 * 2000


class TestDeltaLogGuards:
    def test_since_impossible_base_returns_none(self):
        """ADVICE r2: a stack base ahead of the log head means the stack
        was built from a different fragment object — must rebuild, not
        silently report 'no deltas'."""
        from pilosa_tpu.core.fragment import _DeltaLog

        log = _DeltaLog()
        log.record(1, ("p1",))
        assert log.since(0, 1) == [("p1",)]
        assert log.since(5, 1) is None  # base > head: impossible bridge
        assert log.since(0, 2) is None  # current > head: unlogged bump

    def test_set_many_stops_recording_after_midloop_reset(self, monkeypatch):
        """ADVICE r2: after record() overflows and resets mid-import, the
        remaining payloads are unreplayable (base == their version) and
        must not burn the fresh log's budget."""
        from pilosa_tpu.core import fragment as fragmod

        monkeypatch.setattr(fragmod, "_DELTA_MAX_OPS", 4)
        frag = fragmod.SetFragment(0)
        for r in range(8):
            frag.set_bit(r, 0)  # pre-create rows (new rows reset anyway)
        frag.deltas.reset(frag.version)
        # 8 existing rows in one bulk import: records overflow at op 5
        frag.set_many(list(range(8)), [100 + r for r in range(8)])
        assert frag.deltas.base == frag.version
        assert len(frag.deltas.ops) == 0  # nothing recorded post-reset
        # the NEXT write gets the full fresh budget
        changed = frag.set_bit(0, 200)
        assert changed
        assert len(frag.deltas.ops) == 1


class TestWriteQcxIsolation:
    def test_stack_built_inside_write_qcx_not_published(self):
        """ADVICE r2 (api.py:107): a stack built mid-write-request must
        not be published where concurrent lock-free readers could observe
        the request's intermediate state."""
        from pilosa_tpu.core.stacked import stacked_set
        from pilosa_tpu.storage.txn import TxFactory

        h = Holder()
        idx = h.create_index("i")
        idx.create_field("f")
        f = idx.field("f")
        f.fragment(0, create=True).set_bit(1, 5)
        txf = TxFactory(h)
        with txf.qcx():
            st = stacked_set(f, [0], "standard")
            assert st is not None
            cache = getattr(f, "_stacked_cache", {})
            assert not any(
                inner for inner in cache.values()
            ), "stack published during write Qcx"
        # outside the Qcx the same build publishes normally
        st2 = stacked_set(f, [0], "standard")
        cache = getattr(f, "_stacked_cache", {})
        assert any(inner for inner in cache.values())
        # and is served back on the next call
        assert stacked_set(f, [0], "standard") is st2
