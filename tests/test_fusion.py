"""Cross-shard-set fusion: masked superset execution must be
bit-identical to unfused per-subset execution.

Property under test (pql/executor.py ShardMask): for ANY read query of a
fusible family and ANY shard subset, executing it masked over the union
stacked layout returns byte-for-byte the result of executing it solo
over just its own shards — including single-shard subsets, subsets with
empty pairwise intersection, and data that never intersects the mask.
Everything runs deterministically under JAX_PLATFORMS=cpu.
"""

import random

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.pql.result import result_to_json
from pilosa_tpu.shardwidth import SHARD_WIDTH

N_SHARDS = 8


@pytest.fixture(scope="module")
def fusion_api():
    """8 shards of set + BSI data (negatives included) so every family
    has non-trivial per-shard answers: city rows differ per column,
    amt values span sign and magnitude."""
    api = API()
    api.create_index("fz")
    api.create_field("fz", "city")
    api.create_field("fz", "device")
    api.create_field("fz", "amt", {"type": "int", "min": -100, "max": 200})
    rng = random.Random(1234)
    cols, cities, dcols, devices, vcols, vals = [], [], [], [], [], []
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        for i in rng.sample(range(600), 80):
            cols.append(base + i)
            cities.append((i + shard) % 5)
            dcols.append(base + i)
            devices.append(i % 3)
            vcols.append(base + i)
            vals.append(rng.randrange(-60, 120))
    api.import_bits("fz", "city", rows=cities, cols=cols)
    api.import_bits("fz", "device", rows=devices, cols=dcols)
    api.import_values("fz", "amt", cols=vcols, values=vals)
    return api


# One representative query per family branch the mask threads through:
# count / bitmap (incl. Not+existence, Shift, UnionRows limit) / agg
# (Sum, Min/Max, Percentile) / rank (TopN, Rows, GroupBy) / Distinct.
FAMILY_QUERIES = [
    "Count(Row(city=1))",
    "Count(Intersect(Row(city=0), Row(device=1)))",
    "Count(Row(amt > 10))",
    "Row(city=2)",
    "Union(Row(city=0), Row(city=3))",
    "Difference(Row(city=1), Row(device=0))",
    "Xor(Row(city=1), Row(city=2))",
    "Not(Row(city=1))",
    "Shift(Row(city=4), n=2)",
    "UnionRows(Rows(city, limit=3))",
    "Limit(Row(city=0), limit=7, offset=2)",
    "Sum(Row(city=1), field=amt)",
    "Sum(field=amt)",
    "Min(field=amt)",
    "Max(Row(device=2), field=amt)",
    "Percentile(field=amt, nth=50)",
    "TopN(city, n=3)",
    "TopK(device, k=2)",
    "Rows(city)",
    "Rows(city, limit=2)",
    "GroupBy(Rows(city))",
    "GroupBy(Rows(city), Rows(device), aggregate=Sum(field=amt))",
    "Distinct(field=city)",
    "Count(Distinct(field=amt))",
]

# Subset shapes: single shard, half sets with empty pairwise
# intersection, interleaved, full set, and edges-only.
SUBSETS = [
    [0, 1, 2, 3],
    [4, 5, 6, 7],  # empty intersection with the previous
    [2],           # single shard
    [1, 3, 5, 7],
    list(range(N_SHARDS)),
    [0, 7],
]


def _solo(api, query, shards):
    return [result_to_json(r)
            for r in api.executor.execute("fz", query, shards=shards)]


class TestMaskedSupersetParity:
    @pytest.mark.parametrize("query", FAMILY_QUERIES)
    def test_each_family_bit_identical_across_subsets(self, fusion_api,
                                                      query):
        api = fusion_api
        queries = [query] * len(SUBSETS)
        fused = api.executor.execute_many("fz", queries,
                                          per_query_shards=SUBSETS)
        for shards, got in zip(SUBSETS, fused):
            want = _solo(api, query, shards)
            assert [result_to_json(r) for r in got] == want, shards

    def test_mixed_families_one_fused_round(self, fusion_api):
        """One execute_many over heterogeneous queries AND subsets —
        the realistic merged-batch shape."""
        api = fusion_api
        rng = random.Random(99)
        queries, subsets = [], []
        for _ in range(24):
            queries.append(rng.choice(FAMILY_QUERIES))
            subsets.append(sorted(rng.sample(range(N_SHARDS), 4)))
        fused = api.executor.execute_many("fz", queries,
                                          per_query_shards=subsets)
        for q, s, got in zip(queries, subsets, fused):
            assert [result_to_json(r) for r in got] == _solo(api, q, s)

    def test_empty_subset_matches_solo(self, fusion_api):
        api = fusion_api
        fused = api.executor.execute_many(
            "fz", ["Count(Row(city=1))", "Count(Row(city=1))"],
            per_query_shards=[[], [0, 1]])
        assert fused[0] == _solo(api, "Count(Row(city=1))", [])
        assert fused[1] == _solo(api, "Count(Row(city=1))", [0, 1])

    def test_unmaskable_query_keeps_own_shards(self, fusion_api):
        """A scan-family query in a fused round runs over its own shard
        list (no mask) and still returns exact results."""
        api = fusion_api
        q_scan = "Extract(Row(city=1), Rows(device))"
        q_count = "Count(Row(city=1))"
        fused = api.executor.execute_many(
            "fz", [q_scan, q_count], per_query_shards=[[2, 3], [0, 1]])
        assert [result_to_json(r) for r in fused[0]] == _solo(
            api, q_scan, [2, 3])
        assert fused[1] == _solo(api, q_count, [0, 1])

    def test_per_query_shards_length_mismatch_rejected(self, fusion_api):
        with pytest.raises(ValueError):
            fusion_api.executor.execute_many(
                "fz", ["Count(Row(city=1))"], per_query_shards=[[0], [1]])


class TestFusedCacheFill:
    def test_superset_run_fills_exact_per_query_entries(self, fusion_api):
        """A masked superset dispatch must warm the cache under each
        query's OWN shard set: a later solo read of the same (query,
        subset) is a hit, and a read over a different subset is not."""
        api = fusion_api
        api.enable_cache()
        try:
            cache = api.cache
            q = "Count(Row(city=3))"
            fused = api.executor.execute_many(
                "fz", [q, q], per_query_shards=[[0, 1], [4, 5]])
            h0 = cache.stats()["hits"]
            again = api.executor.execute("fz", q, shards=[0, 1])
            assert cache.stats()["hits"] == h0 + 1
            assert again == fused[0]
            # different subset: its own entry, filled by the same round
            assert api.executor.execute("fz", q, shards=[4, 5]) == fused[1]
            assert cache.stats()["hits"] == h0 + 2
            # union itself was never executed as a query -> miss
            hits_before = cache.stats()["hits"]
            api.executor.execute("fz", q, shards=[0, 1, 4, 5])
            assert cache.stats()["hits"] == hits_before
        finally:
            api.disable_cache()

    def test_cached_superset_round_is_one_dispatch(self, fusion_api):
        api = fusion_api
        api.enable_cache()
        try:
            reg = MetricsRegistry()
            sched = api.enable_scheduler(window_ms=0, max_batch=64,
                                         fuse_waste_ratio=8.0, registry=reg)
            sched.pause()
            handles = [
                sched.submit("fz", f"Count(Row(city={k}))", shards=s)
                for k, s in enumerate(([0, 1], [1, 2], [2, 3], [3, 4]))]
            assert sched.wait_queued(4) == 4
            sched.resume()
            got = [h.result(timeout=10)[0] for h in handles]
            want = [api.executor.execute(
                "fz", f"Count(Row(city={k}))", shards=s)[0]
                for k, s in enumerate(([0, 1], [1, 2], [2, 3], [3, 4]))]
            assert got == want
            counters = reg.as_json()["counters"]
            batches = sum(v for k, v in counters.items()
                          if k.startswith("sched_batches_total"))
            assert batches == 1
            merges = sum(v for k, v in counters.items()
                         if k.startswith("sched_superset_merges_total"))
            assert merges == 3
        finally:
            api.disable_scheduler()
            api.disable_cache()


class TestFusionMetricsExposition:
    def test_padding_waste_histogram_and_names(self, fusion_api):
        from pilosa_tpu.obs import metrics as M

        api = fusion_api
        reg = MetricsRegistry()
        sched = api.enable_scheduler(window_ms=0, max_batch=64,
                                     fuse_waste_ratio=8.0, registry=reg)
        try:
            sched.pause()
            hs = [sched.submit("fz", "Count(Row(city=1))", shards=[0, 1]),
                  sched.submit("fz", "Count(Row(city=2))", shards=[2, 3])]
            assert sched.wait_queued(2) == 2
            sched.resume()
            for h in hs:
                h.result(timeout=10)
            text = reg.prometheus_text()
            assert "sched_superset_merges_total" in text
            assert "sched_fused_queries_total" in text
            assert "sched_padding_waste_ratio" in text
            j = reg.as_json()
            assert reg.value(M.METRIC_SCHED_SUPERSET_MERGES,
                             family="count") == 1
            # union of {0,1} and {2,3} is 4 shards over max subset 2 -> 2.0
            waste = [k for k in j["histograms"]
                     if k.startswith(M.METRIC_SCHED_PADDING_WASTE)]
            assert waste
        finally:
            api.disable_scheduler()
