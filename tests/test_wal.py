"""WAL durability and crash recovery.

The executable spec of storage/wal.py + Holder.recover: every write class
survives a process "crash" (drop the API object, reopen from disk with NO
explicit save), torn tails are tolerated, and checkpoints truncate
(reference test analogs: rbf/db_test.go WAL tests, dax writelogger tests).
"""

import os

import numpy as np
import pytest

from pilosa_tpu.api import API
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage.wal import WAL


def reopen(tmp_path) -> API:
    return API(str(tmp_path))


class TestWALFraming:
    def test_roundtrip_and_torn_tail(self, tmp_path):
        w = WAL(str(tmp_path / "x" / "wal.log"), sync="never")
        recs = [("a", 1), ("b", [1, 2, 3]), ("c", {"k": "v"})]
        for r in recs:
            w.append(r)
        w.flush()
        assert list(w.records()) == recs
        # torn tail: append garbage half-record
        with open(w.path, "ab") as f:
            f.write(b"\x01\x02\x03")
        assert list(w.records()) == recs
        # corrupt a middle record -> replay stops before it
        data = open(w.path, "rb").read()
        with open(w.path, "wb") as f:
            f.write(data[:10] + b"\xff" + data[11:])
        assert list(w.records()) == []
        w.close()

    def test_truncate(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"), sync="never")
        w.append(("x",))
        w.truncate()
        w.append(("y",))
        w.flush()
        assert list(w.records()) == [("y",)]
        w.close()


class TestCrashRecovery:
    def test_writes_survive_without_save(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.create_field("i", "n", {"type": "int"})
        api.query("i", "Set(1, f=3)Set(2, f=3)Set(1, n=42)")
        big = 2 * SHARD_WIDTH + 5
        api.import_bits("i", "f", rows=[7, 7], cols=[9, big])
        api.import_values("i", "n", cols=[big], values=[-6])
        del api

        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=3)")[0].columns == [1, 2]
        assert api2.query("i", "Row(f=7)")[0].columns == [9, big]
        assert api2.query("i", "Sum(field=n)")[0].val == 36
        assert api2.query("i", "Count(All())")[0] == 4

    def test_clears_and_deletes_survive(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=3)Set(2, f=3)Set(3, f=3)")
        api.query("i", "Clear(2, f=3)")
        api.query("i", "Delete(Row(f=9))")  # no-op delete
        api.query("i", "Set(5, f=4)")
        api.query("i", "Delete(ConstRow(columns=[3]))")
        want_row = api.query("i", "Row(f=3)")[0].columns
        want_all = api.query("i", "Count(All())")[0]
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=3)")[0].columns == want_row == [1]
        # Clear() removes the bit but not existence (reference semantics),
        # so {1,2,5} remain after Delete(col 3).
        assert api2.query("i", "Count(All())")[0] == want_all == 3

    def test_store_and_clearrow_survive(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)Set(2, f=1)Set(2, f=2)")
        api.query("i", "Store(Row(f=1), f=9)")
        api.query("i", "ClearRow(f=2)")
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=9)")[0].columns == [1, 2]
        assert api2.query("i", "Row(f=2)")[0].columns == []

    def test_recovery_after_checkpoint_plus_tail(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        api.save()  # checkpoint: snapshot + WAL segments pruned
        assert api.holder.index("i").wal.record_bytes == 0
        api.query("i", "Set(2, f=1)")  # tail after checkpoint
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=1)")[0].columns == [1, 2]

    def test_torn_tail_drops_only_last_write(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        wal = api.holder.index("i").wal
        size_after_first = wal.size
        api.query("i", "Set(2, f=1)")
        wal_path = wal.path
        del api
        # crash mid-append: cut into the first record of the second Set
        with open(wal_path, "r+b") as f:
            f.truncate(size_after_first + 4)
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=1)")[0].columns == [1]

    def test_mutex_and_time_fields_replay(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "m", {"type": "mutex"})
        api.create_field("i", "t", {"type": "time", "timeQuantum": "YMD"})
        api.query("i", "Set(1, m=1)")
        api.query("i", "Set(1, m=2)")  # mutex: replaces row 1
        api.query("i", 'Set(3, t=5, 2024-05-01T00:00)')
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(m=1)")[0].columns == []
        assert api2.query("i", "Row(m=2)")[0].columns == [1]
        got = api2.query(
            "i", "Row(t=5, from=2024-04-01T00:00, to=2024-06-01T00:00)")[0]
        assert got.columns == [3]

    def test_auto_checkpoint_threshold(self, tmp_path):
        api = API(str(tmp_path))
        api.holder.checkpoint_bytes = 1  # force
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        # qcx.finish ran maybe_checkpoint -> records pruned, snapshot exists
        assert api.holder.index("i").wal.record_bytes == 0
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=1)")[0].columns == [1]


class TestQcx:
    def test_qcx_flushes_dirty_wals(self, tmp_path):
        api = API(str(tmp_path), wal_sync="batch")
        api.create_index("i")
        api.create_field("i", "f")
        with api.txf.qcx():
            api.holder.index("i").field("f").set_bit(1, 2)
        w = api.holder.index("i").wal
        assert list(w.records())  # flushed and readable


class TestReviewRegressions:
    def test_double_restart_after_torn_tail(self, tmp_path):
        # recover() must repair the torn tail so post-recovery writes are
        # not appended behind garbage (and lost on the NEXT restart).
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        wal_path = api.holder.index("i").wal.path
        del api
        with open(wal_path, "ab") as f:
            f.write(b"\xde\xad\xbe")  # torn tail
        api2 = reopen(tmp_path)
        api2.query("i", "Set(2, f=1)")  # write AFTER recovery
        del api2
        api3 = reopen(tmp_path)
        assert api3.query("i", "Row(f=1)")[0].columns == [1, 2]

    def test_rejected_write_does_not_poison_wal(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "n", {"type": "int", "min": 0, "max": 100})
        api.import_values("i", "n", cols=[1], values=[50])
        with pytest.raises(ValueError):
            api.import_values("i", "n", cols=[2], values=[10**9])
        del api
        api2 = reopen(tmp_path)  # must not raise
        assert api2.query("i", "Sum(field=n)")[0].val == 50

    def test_delete_index_removes_data_dir(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        api.save()  # checkpoint persists npz fragments
        api.delete_index("i")
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(9, f=1)")
        del api
        api2 = reopen(tmp_path)
        # the deleted index's planes must NOT resurrect
        assert api2.query("i", "Row(f=1)")[0].columns == [9]

    def test_delete_records_one_wal_record_per_shard(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        for fn in ("a", "b", "c"):
            api.create_field("i", fn)
        api.query("i", "Set(1, a=1)Set(1, b=1)Set(1, c=1)")
        wal = api.holder.index("i").wal
        before = sum(1 for _ in wal.records())
        api.query("i", "Delete(ConstRow(columns=[1]))")
        recs = list(wal.records())[before:]
        assert [r[0] for r in recs] == ["delete_cols"]
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Count(All())")[0] == 0
        assert api2.query("i", "Row(a=1)")[0].columns == []

    def test_batch_existence_survives_crash(self, tmp_path):
        from pilosa_tpu.ingest.batch import Batch
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        b = Batch(api, "i", size=10)
        b.add({"id": 5, "f": 1})
        b.add({"id": 6})  # all-None record: existence only
        b.flush()
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Count(All())")[0] == 2


class TestTombstones:
    def test_dataframe_delete_survives_reopen(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("t")
        api.import_dataframe("t", 0, [1], {"fare": [5.0]})
        api.delete_dataframe("t")
        del api
        api2 = reopen(tmp_path)
        assert api2.query("t", 'Apply("sum(fare)")')[0].value == 0
        assert api2.dataframe_schema("t") == []

    def test_field_delete_recreate_no_resurrection(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")
        api.save()  # checkpoint writes npz for f
        api.delete_field("i", "f")
        api.create_field("i", "f")
        api.query("i", "Set(9, f=2)")
        del api
        api2 = reopen(tmp_path)
        assert api2.query("i", "Row(f=1)")[0].columns == []
        assert api2.query("i", "Row(f=2)")[0].columns == [9]

    def test_concurrent_writers_no_wal_corruption(self, tmp_path):
        import threading

        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")

        def worker(row):
            for c in range(50):
                api.query("i", f"Set({c}, f={row})")

        threads = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        del api
        api2 = reopen(tmp_path)
        for r in range(4):
            assert api2.query("i", f"Count(Row(f={r}))")[0] == 50

    def test_sql_dml_survives_without_save(self, tmp_path):
        """SQL writes must get the same Qcx group-commit as PQL writes
        (advisor r1 high: acknowledged INSERTs were lost on crash under
        wal_sync="batch" because no flush_wals ran)."""
        api = API(str(tmp_path))
        api.sql("create table t (_id id, f stringset, n int)")
        api.sql("insert into t (_id, f, n) values (1, 'a', 7)")
        api.sql("insert into t (_id, f, n) values (2, 'b', 5)")
        api.sql("delete from t where _id = 2")
        del api

        api2 = reopen(tmp_path)
        got = api2.sql("select _id, n from t order by _id")
        assert got.data == [[1, 7]]

    def test_read_queries_take_no_write_lock(self, tmp_path):
        """Pure reads must not serialize behind the holder write lock
        (advisor r1 low: every query used to enter a Qcx)."""
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=2)")
        api.query("i", "Row(f=2)")  # warm the stacked cache
        # cache-hit reads never need the lock (cache-MISS builds do
        # briefly serialize against writers — the torn-read guard)
        with api.holder.write_lock:
            # RLock is reentrant in the owning thread, so probe from
            # another thread with a short timeout.
            import threading

            out = {}

            def read():
                out["cols"] = api.query("i", "Row(f=2)")[0].columns

            t = threading.Thread(target=read)
            t.start()
            t.join(timeout=30)
            assert out.get("cols") == [1], "read blocked on write lock"

    def test_concurrent_reads_and_writes_no_torn_state(self, tmp_path):
        """Lock-free reads must never crash on (or cache) a half-applied
        write: stack builds serialize against writers internally."""
        import threading

        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(0, f=0)")
        stop = threading.Event()
        errors = []

        def writer():
            r = 0
            while not stop.is_set():
                r += 1
                try:
                    api.query("i", f"Set({r % 100}, f={r})")
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        def reader():
            while not stop.is_set():
                try:
                    api.query("i", "TopN(f, n=5)")
                    api.query("i", "Count(Row(f=0))")
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        import time

        time.sleep(3)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
