"""Driver entry-point smoke tests (these rot silently otherwise)."""

import pathlib
import sys

import jax
import numpy as np
import pytest

# Repo root holds __graft_entry__.py; don't depend on pytest's cwd.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert set(out) == {"count", "row_counts", "top_vals", "top_idx",
                        "bsi_plane_counts", "groupby"}
    # count equals row 0's filtered popcount
    s, b, f = args
    expect = g._np_popcount(np.asarray(s)[:, 0, :] & np.asarray(f))
    assert int(out["count"]) == expect


@pytest.mark.parametrize("n", [1, 2, 8])
def test_dryrun_multichip(n):
    import __graft_entry__ as g

    g.dryrun_multichip(n)  # asserts internally against numpy oracle
