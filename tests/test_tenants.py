"""Tenant attribution plane (pilosa_tpu/obs/tenants.py + wiring).

Covers the whole vertical: untrusted-ID clamping, the bounded
accounting registry and its top-K publication guard, token-bucket
quotas (429 + Retry-After at the HTTP edge), the per-tenant SLO burn
dimension and its alert edge cases, weighted-fair scheduler ordering,
tenant-scoped cache namespaces/quotas, the WAL attribution hook, and a
3-node LocalCluster acceptance pass ending in a tenant_burn flight
bundle. Deterministic clocks throughout (FakeClock for the registry's
callable clock, sched.ManualClock for the SLO tracker).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.errors import QuotaExceededError
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs import tenants as T
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.obs.slo import Objective, SLOTracker
from pilosa_tpu.obs.tenants import (
    DEFAULT_TENANT, OVERFLOW_TENANT, TenantRegistry, TokenBucket,
    current_tenant_id, normalize_tenant, tenant_scope,
)
from pilosa_tpu.sched import ManualClock, QueryScheduler
from pilosa_tpu.server.http import serve


class FakeClock:
    """Callable monotonic stand-in for TenantRegistry's ``clock()``."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def make_registry(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("clock", FakeClock())
    return TenantRegistry(**kw)


# -- clamping (satellite 3) ------------------------------------------------


class TestNormalize:
    def test_valid_ids_pass_through(self):
        assert normalize_tenant("acme") == ("acme", True)
        assert normalize_tenant("  t-1.2_x  ") == ("t-1.2_x", True)
        assert normalize_tenant("A" * T.MAX_TENANT_LEN) == \
            ("A" * T.MAX_TENANT_LEN, True)

    @pytest.mark.parametrize("raw", [
        None, "", "   ", "x" * (T.MAX_TENANT_LEN + 1),  # absent/empty/big
        "café", "tenant name", "a/b", "x\x00y",    # non-slug bytes
    ])
    def test_garbage_clamps_to_default(self, raw):
        assert normalize_tenant(raw) == (DEFAULT_TENANT, False)

    def test_non_str_coerces(self):
        # header values are str in practice, but resolve() must never
        # raise on anything a caller hands it
        assert normalize_tenant(123) == ("123", True)

    def test_resolve_counts_unattributed(self):
        reg = make_registry()
        assert reg.resolve("ok-tenant") == "ok-tenant"
        assert reg.registry.value(M.METRIC_TENANT_UNATTRIBUTED) == 0
        assert reg.resolve("") == DEFAULT_TENANT
        assert reg.resolve("bad tenant!") == DEFAULT_TENANT
        assert reg.registry.value(M.METRIC_TENANT_UNATTRIBUTED) == 2


# -- scope ------------------------------------------------------------------


class TestScope:
    def test_scope_sets_and_restores(self):
        assert current_tenant_id() is None
        with tenant_scope("a"):
            assert current_tenant_id() == "a"
            with tenant_scope("b"):
                assert current_tenant_id() == "b"
            assert current_tenant_id() == "a"
        assert current_tenant_id() is None

    def test_scope_count_moves_only_inside_scopes(self):
        before = T.SCOPE_COUNT
        current_tenant_id()
        assert T.SCOPE_COUNT == before  # reads are free
        with tenant_scope("a"):
            pass
        assert T.SCOPE_COUNT == before + 1


# -- accounting registry ----------------------------------------------------


class TestRegistryAccounting:
    def test_note_accumulates_every_dimension(self):
        reg = make_registry()
        reg.note("a", queries=2, errors=1, rows=10, device_seconds=0.5,
                 cache_hits=3, cache_bytes=100, wal_bytes=7)
        reg.note("a", queries=1, wal_bytes=3)
        row = reg.stats_json()["tenants"]["a"]
        assert row["queries"] == 3
        assert row["errors"] == 1
        assert row["rows_ingested"] == 10
        assert row["device_seconds"] == 0.5
        assert row["cache_hits"] == 3
        assert row["cache_bytes"] == 100
        assert row["wal_bytes"] == 10

    def test_none_tenant_lands_on_default(self):
        reg = make_registry()
        reg.note_query(None)
        reg.note_query(None, error=True)
        row = reg.stats_json()["tenants"][DEFAULT_TENANT]
        assert row["queries"] == 2 and row["errors"] == 1

    def test_max_tracked_folds_into_overflow_cell(self):
        reg = make_registry(max_tracked=3)
        for i in range(5):
            reg.note_query(f"t{i}")
        st = reg.stats_json()
        # t0..t2 tracked individually; t3/t4 share the overflow cell
        assert set(st["tenants"]) == {"t0", "t1", "t2", OVERFLOW_TENANT}
        assert st["dropped"] == 2
        assert st["tenants"][OVERFLOW_TENANT]["queries"] == 2
        assert st["max_tracked"] == 3

    def test_publish_guards_label_space_to_top_k(self):
        reg = make_registry(top_k=2)
        for i, n in enumerate([10, 5, 1, 1]):
            reg.note("t%d" % i, queries=n)
        reg.publish()
        mreg = reg.registry
        assert mreg.value(M.METRIC_TENANT_TRACKED) == 4
        assert mreg.value(M.METRIC_TENANT_QUERIES, tenant="t0") == 10
        assert mreg.value(M.METRIC_TENANT_QUERIES, tenant="t1") == 5
        # below the K cut: no gauge series exists for t2/t3
        assert mreg.value(M.METRIC_TENANT_QUERIES, tenant="t2") == 0.0
        assert reg.stats_json()["top_k"] == ["t0", "t1"]
        # ...but the raw endpoint payload still carries every tenant
        assert set(reg.stats_json()["tenants"]) == {"t0", "t1", "t2", "t3"}

    def test_timeline_probe_reports_rates_between_calls(self):
        clock = FakeClock()
        reg = make_registry(clock=clock)
        reg.note("a", queries=4, rows=8)
        first = reg.timeline_probe()
        assert first["enabled"] is True and first["rates"] == {}
        reg.note("a", queries=10, rows=20)
        clock.advance(2.0)
        probe = reg.timeline_probe()
        assert probe["rates"]["a"]["qps"] == pytest.approx(5.0)
        assert probe["rates"]["a"]["rows_per_s"] == pytest.approx(10.0)


# -- quotas -----------------------------------------------------------------


class TestQuotas:
    def test_token_bucket_refills_and_reports_retry(self):
        b = TokenBucket(rate=2.0, burst=2.0, now=0.0)
        assert b.take(2.0, now=0.0) is None
        retry = b.take(1.0, now=0.0)
        assert retry == pytest.approx(0.5)
        assert b.take(1.0, now=0.5) is None  # refilled exactly enough

    def test_rate_zero_is_unlimited(self):
        b = TokenBucket(rate=0.0, burst=1.0, now=0.0)
        for _ in range(100):
            assert b.take(1.0, now=0.0) is None
        reg = make_registry()  # default quotas are 0 = attribution only
        for _ in range(100):
            reg.charge_query("free")
        assert reg.registry.value(M.METRIC_TENANT_REJECTED,
                                  tenant="free", kind="qps") == 0

    def test_qps_quota_rejects_with_retry_after(self):
        clock = FakeClock()
        reg = make_registry(clock=clock)
        reg.set_quota("spam", qps=2.0)  # burst = 2.0 * qps_burst_s(2) = 4
        for _ in range(4):
            reg.charge_query("spam")
        with pytest.raises(QuotaExceededError) as ei:
            reg.charge_query("spam")
        assert ei.value.retry_after_s == pytest.approx(0.5)
        assert reg.registry.value(M.METRIC_TENANT_REJECTED,
                                  tenant="spam", kind="qps") == 1
        assert reg.stats_json()["tenants"]["spam"]["rejected"] == 1
        clock.advance(0.5)  # one token refilled
        reg.charge_query("spam")

    def test_ingest_quota_charges_rows(self):
        clock = FakeClock()
        reg = make_registry(clock=clock)
        reg.set_quota("bulk", ingest_rows_s=10.0)  # burst 20
        reg.charge_ingest("bulk", 20)
        with pytest.raises(QuotaExceededError) as ei:
            reg.charge_ingest("bulk", 1)
        assert ei.value.retry_after_s == pytest.approx(0.1)
        assert reg.registry.value(M.METRIC_TENANT_REJECTED,
                                  tenant="bulk", kind="ingest") == 1
        reg.charge_ingest("bulk", 0)  # zero rows never charges

    def test_set_quota_rerate_drops_old_bucket(self):
        reg = make_registry()
        reg.set_quota("t", qps=1.0)  # burst 2
        reg.charge_query("t")
        reg.charge_query("t")
        with pytest.raises(QuotaExceededError):
            reg.charge_query("t")
        reg.set_quota("t", qps=100.0)  # fresh bucket at full burst
        reg.charge_query("t")

    def test_weights_default_and_floor(self):
        reg = make_registry()
        assert reg.weight("anyone") == 1.0
        reg.set_weight("vip", 4.0)
        assert reg.weight("vip") == 4.0
        reg.set_weight("zero", 0.0)  # clamped, never divides by zero
        assert reg.weight("zero") > 0


# -- SLO tenant dimension + edge cases (satellite 2) ------------------------


class TestSLOTenants:
    def make(self, **kw):
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("clock", ManualClock())
        return SLOTracker(**kw)

    def test_no_tenant_events_is_free(self):
        slo = self.make()
        slo.record("query", 1.0)  # untagged traffic only
        assert slo.tenant_burn_rates() == []
        assert slo.tenant_alerting() == []

    def test_per_tenant_burn_and_gauges(self):
        slo = self.make()
        for _ in range(10):
            slo.record("query", 1.0, error=True, tenant="mallory")
            slo.record("query", 1.0, tenant="alice")
        rows = {(r["tenant"], r["name"]): r for r in slo.tenant_burn_rates()}
        bad = rows[("mallory", "query-errors")]
        good = rows[("alice", "query-errors")]
        assert bad["alerting"] and bad["fast_burn"] >= 10.0
        assert not good["alerting"] and good["fast_burn"] == 0.0
        v = slo.registry.value(M.METRIC_SLO_BURN_RATE, slo="query-errors",
                               tenant="mallory", window="fast")
        assert v == pytest.approx(bad["fast_burn"])
        assert slo.status()["tenants"]  # status carries the rows too
        assert [r["tenant"] for r in slo.tenant_alerting()] == ["mallory"]

    def test_tenant_cap_folds_hostile_ids(self):
        slo = self.make()
        slo.tenant_cap = 3
        for i in range(6):
            slo.record("query", 1.0, tenant=f"t{i}")
        tenants = {r["tenant"] for r in slo.tenant_burn_rates()}
        assert tenants == {"t0", "t1", "t2", "__other__"}

    def test_window_boundary_at_exactly_slow_window(self):
        clock = ManualClock()
        slo = self.make(clock=clock, bucket_s=5.0, slow_window_s=3600.0)
        slo.record("query", 1.0, error=True, tenant="a")  # bucket t=0
        clock.advance(3600.0)
        row = slo.tenant_burn_rates()[0]
        # cutoff == bucket start: the bucket's span (0, 5] still
        # overlaps the window, so the event counts...
        assert row["events_slow"] == 1
        clock.advance(5.0)
        # ...and ages out exactly one bucket width later
        assert slo.tenant_burn_rates() == [] or \
            slo.tenant_burn_rates()[0]["events_slow"] == 0

    def test_min_events_boundary(self):
        slo = self.make(min_events=5)
        for _ in range(4):
            slo.record("query", 1.0, error=True, tenant="m")
        rows = {r["name"]: r for r in slo.tenant_burn_rates()}
        # burn is sky-high but 4 < min_events: a blip must not page
        assert rows["query-errors"]["fast_burn"] > 100
        assert not rows["query-errors"]["alerting"]
        slo.record("query", 1.0, error=True, tenant="m")
        rows = {r["name"]: r for r in slo.tenant_burn_rates()}
        assert rows["query-errors"]["alerting"]

    def test_target_one_has_zero_budget_but_never_divides_by_zero(self):
        objs = [Objective("strict", "query", "errors", 1.0)]
        slo = self.make(objectives=objs)
        slo.record("query", 1.0, tenant="a")
        rows = slo.tenant_burn_rates()
        assert rows[0]["fast_burn"] == 0.0  # no bad events: zero burn
        slo.record("query", 1.0, error=True, tenant="a")
        rows = slo.tenant_burn_rates()
        assert rows[0]["fast_burn"] > 1e6  # one bad event: burn explodes
        # overall evaluation path hits the same budget clamp
        assert slo.burn_rates()[0]["fast_burn"] > 1e6


# -- weighted-fair scheduler ordering ---------------------------------------


class StubExecutor:
    def __init__(self):
        self.calls = []
        self._lock = threading.Lock()

    def execute(self, index, query, shards=None):
        with self._lock:
            self.calls.append(index)
        return [c.to_pql() for c in query.calls]


@pytest.fixture
def make_sched():
    created = []

    def make(executor, **kw):
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("window_ms", 0)
        s = QueryScheduler(executor, **kw)
        created.append(s)
        return s

    yield make
    for s in created:
        s.close()


class TestFairShare:
    def test_higher_weight_tenant_dispatches_first(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, fair_share=True)
        s.set_fair_share(True, lambda t: 4.0 if t == "light" else 1.0)
        s.pause()
        handles = []
        # one group key per submit (distinct index) so dispatch order is
        # purely the (rank, vtime, seq) head pick, no batching
        with tenant_scope("heavy"):
            for i in range(4):
                handles.append(s.submit(f"h{i}", "Count(Row(f=1))"))
        with tenant_scope("light"):
            for i in range(4):
                handles.append(s.submit(f"l{i}", "Count(Row(f=1))"))
        assert s.wait_queued(8) == 8
        s.resume()
        for h in handles:
            h.result(timeout=5)
        # heavy strides 1 -> vtimes 1,2,3,4; light strides 1/4 ->
        # .25,.5,.75,1.0; the 1.0 tie breaks on seq (heavy arrived first)
        assert stub.calls == ["l0", "l1", "l2", "h0", "l3",
                              "h1", "h2", "h3"]

    def test_fair_off_is_strict_fifo(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub)  # fair_share defaults False
        s.pause()
        handles = []
        for i, t in enumerate(["a", "b", "a", "b"]):
            with tenant_scope(t):
                handles.append(s.submit(f"q{i}", "Count(Row(f=1))"))
        assert s.wait_queued(4) == 4
        s.resume()
        for h in handles:
            h.result(timeout=5)
        assert stub.calls == ["q0", "q1", "q2", "q3"]

    def test_toggle_clears_vtime_state_and_shows_in_stats(self, make_sched):
        s = make_sched(StubExecutor(), fair_share=True)
        assert s.stats()["fair_share"] is True
        s.pause()
        with tenant_scope("t"):
            h = s.submit("i", "Count(Row(f=1))")
        s.resume()
        h.result(timeout=5)
        s.set_fair_share(False)
        assert s.stats()["fair_share"] is False
        assert s._tenant_vtime == {}


# -- cache: tenant namespaces + resident quota ------------------------------


class TestCacheTenancy:
    def test_executor_namespace_splits_per_tenant(self):
        api = API()
        api.create_index("i")
        api.create_field("i", "f")
        ex = api.executor
        base = ex.cache_key("i", "Count(Row(f=1))")
        ex.tenant_namespaces = True
        try:
            with tenant_scope("a"):
                ka = ex.cache_key("i", "Count(Row(f=1))")
                assert ka == ex.cache_key("i", "Count(Row(f=1))")
            with tenant_scope("b"):
                kb = ex.cache_key("i", "Count(Row(f=1))")
            # out of scope: back to the shared namespace
            assert ex.cache_key("i", "Count(Row(f=1))") == base
            assert len({ka, kb, base}) == 3
        finally:
            ex.tenant_namespaces = False

    def test_cache_hook_attributes_hits_and_bytes(self):
        from pilosa_tpu.cache.result_cache import ResultCache

        reg = make_registry()
        cache = ResultCache(registry=MetricsRegistry())
        cache.tenant_hook = reg.cache_hook
        cache.tenant_of = current_tenant_id
        with tenant_scope("a"):
            cache.insert(("k1",), [1, 2, 3])
            hit, _ = cache.lookup(("k1",))
            assert hit
        row = reg.stats_json()["tenants"]["a"]
        assert row["cache_hits"] == 1
        assert row["cache_bytes"] > 0
        # un-scoped traffic: the hook is a no-op, not a crash
        cache.insert(("k2",), [1])
        cache.lookup(("k2",))
        assert "default" not in reg.stats_json()["tenants"]

    def test_resident_quota_skips_insert_and_credits_on_evict(self):
        from pilosa_tpu.cache.result_cache import ResultCache

        mreg = MetricsRegistry()
        cache = ResultCache(registry=mreg)
        cache.tenant_of = current_tenant_id
        with tenant_scope("a"):
            cache.insert(("k1",), [0] * 100)
            cost = cache._entries[("k1",)].cost
            cache.tenant_quota_bytes = cost + 1
            # second entry would push 'a' past its resident quota:
            # skipped (recompute beats displacing other tenants)
            cache.insert(("k2",), [0] * 100)
            assert cache.lookup(("k2",))[0] is False
            assert mreg.value(M.METRIC_TENANT_REJECTED,
                              tenant="a", kind="cache") == 1
            # eviction credits the tenant's resident bytes back
            cache.flush()
            cache.insert(("k2",), [0] * 100)
            assert cache.lookup(("k2",))[0] is True
            assert cache._tenant_bytes["a"] == cost


# -- WAL + device hooks -----------------------------------------------------


class TestConsumptionHooks:
    def test_wal_hook_chains_and_uninstalls(self):
        from pilosa_tpu.storage import wal as wal_mod

        seen = []
        prev = wal_mod._APPEND_HOOK
        wal_mod.set_append_hook(seen.append)
        reg = make_registry()
        try:
            reg.install_hooks()
            reg.install_hooks()  # re-entrant: second call is a no-op
            with tenant_scope("w"):
                wal_mod._APPEND_HOOK(64)
            wal_mod._APPEND_HOOK(32)  # un-scoped: attributed nowhere
            assert seen == [64, 32]  # the prior hook still fires
            assert reg.stats_json()["tenants"]["w"]["wal_bytes"] == 64
            assert "default" not in reg.stats_json()["tenants"]
            reg.uninstall_hooks()
            assert wal_mod._APPEND_HOOK == seen.append
        finally:
            reg.uninstall_hooks()
            wal_mod.set_append_hook(prev)

    def test_wal_append_attributes_real_bytes(self, tmp_path):
        api = API(path=str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        reg = api.enable_tenants(registry=MetricsRegistry())
        try:
            with tenant_scope("ing"):
                api.query("i", "Set(1, f=1)")
            assert reg.stats_json()["tenants"]["ing"]["wal_bytes"] > 0
        finally:
            api.disable_tenants()


# -- HTTP edge: attribution, 429 + Retry-After, /internal/tenants -----------


def _req(base, path, method="GET", body=None, tenant=None,
         ctype="text/plain"):
    req = urllib.request.Request(
        base + path, method=method,
        data=body.encode() if isinstance(body, str) else body)
    if body is not None:
        req.add_header("Content-Type", ctype)
    if tenant is not None:
        req.add_header("X-Tenant", tenant)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}"), \
                dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


@pytest.fixture
def http_api():
    api = API()
    api.create_index("i")
    api.create_field("i", "f")
    api.query("i", "Set(1, f=1)")
    srv, _ = serve(api, port=0, background=True)
    host, port = srv.server_address[:2]
    try:
        yield api, f"http://{host}:{port}"
    finally:
        srv.shutdown()
        srv.server_close()
        if api.tenants is not None:
            api.disable_tenants()


class TestHTTPTenancy:
    def test_disabled_plane_reports_disabled(self, http_api):
        api, base = http_api
        if api.tenants is not None:  # PILOSA_TPU_TENANTS=1 bootstrap
            api.disable_tenants()
        status, body, _ = _req(base, "/internal/tenants")
        assert status == 200 and body == {"enabled": False}
        # and request handling does zero tenant work
        scope0 = T.SCOPE_COUNT
        status, _, _ = _req(base, "/index/i/query", "POST",
                            "Count(Row(f=1))", tenant="ghost")
        assert status == 200
        assert T.SCOPE_COUNT == scope0

    def test_header_attribution_and_stats_endpoint(self, http_api):
        api, base = http_api
        api.enable_tenants(registry=MetricsRegistry())
        for _ in range(3):
            status, _, _ = _req(base, "/index/i/query", "POST",
                                "Count(Row(f=1))", tenant="acme")
            assert status == 200
        status, body, _ = _req(base, "/internal/tenants")
        assert status == 200 and body["enabled"] is True
        assert body["tenants"]["acme"]["queries"] == 3

    def test_query_param_attribution(self, http_api):
        api, base = http_api
        reg = api.enable_tenants(registry=MetricsRegistry())
        status, _, _ = _req(base, "/index/i/query?tenant=qp-co", "POST",
                            "Count(Row(f=1))")
        assert status == 200
        assert reg.stats_json()["tenants"]["qp-co"]["queries"] == 1

    def test_garbage_tenant_never_400s(self, http_api):
        api, base = http_api
        reg = api.enable_tenants(registry=MetricsRegistry())
        for bad in ["", "x" * 200, "sp ace", "a/b"]:
            status, _, _ = _req(base, "/index/i/query", "POST",
                                "Count(Row(f=1))", tenant=bad)
            assert status == 200
        assert reg.registry.value(M.METRIC_TENANT_UNATTRIBUTED) == 4
        assert reg.stats_json()["tenants"][DEFAULT_TENANT]["queries"] == 4

    def test_quota_exhaustion_is_429_with_retry_after(self, http_api):
        api, base = http_api
        clock = FakeClock()
        reg = api.enable_tenants(registry=MetricsRegistry(), clock=clock)
        reg.set_quota("spam", qps=1.0)  # burst 2
        codes = []
        for _ in range(3):
            status, body, headers = _req(base, "/index/i/query", "POST",
                                         "Count(Row(f=1))", tenant="spam")
            codes.append(status)
        assert codes == [200, 200, 429]
        assert "quota" in body["error"]
        assert int(headers["Retry-After"]) >= 1
        # rejected requests never reach the executor or SLO surface
        assert reg.stats_json()["tenants"]["spam"]["queries"] == 2
        assert reg.stats_json()["tenants"]["spam"]["rejected"] == 1

    def test_ingest_quota_on_import(self, http_api):
        api, base = http_api
        reg = api.enable_tenants(registry=MetricsRegistry(),
                                 clock=FakeClock())
        reg.set_quota("bulk", ingest_rows_s=2.0)  # burst 4
        body = json.dumps({"field": "f", "rows": [1, 1, 1],
                           "cols": [10, 11, 12]})
        status, _, _ = _req(base, "/index/i/import", "POST", body,
                            tenant="bulk", ctype="application/json")
        assert status == 200
        status, _, headers = _req(base, "/index/i/import", "POST", body,
                                  tenant="bulk", ctype="application/json")
        assert status == 429 and "Retry-After" in headers
        row = reg.stats_json()["tenants"]["bulk"]
        assert row["rows_ingested"] == 3 and row["rejected"] == 1


# -- cluster acceptance: attribution + tenant SLO + flight bundle -----------


class TestClusterAcceptance:
    def test_three_nodes_attribute_burn_and_capture_flight(self, tmp_path):
        from pilosa_tpu.cluster.harness import LocalCluster

        with LocalCluster(3, replica_n=2,
                          base_path=str(tmp_path)) as cluster:
            coord = cluster.coordinator
            coord.create_index("ti")
            coord.create_field("ti", "f")
            coord.import_bits("ti", "f", rows=[1] * 64,
                              cols=list(range(64)))
            cluster.enable_tenants()
            cluster.enable_health()
            base = coord.node.uri

            for t in ("alpha", "bravo", "charlie"):
                for _ in range(3):
                    status, body, _ = _req(base, "/index/ti/query", "POST",
                                           "Count(Row(f=1))", tenant=t)
                    assert status == 200
                    assert body["results"] == [64]
            # mallory's traffic is all errors: fast burn 1000x budget
            for _ in range(6):
                status, _, _ = _req(base, "/index/ti/query", "POST",
                                    "Row(nosuch=1)", tenant="mallory")
                assert status >= 400
            # force a timeline sample while the burn is hot so the
            # flight recorder evaluates its triggers deterministically
            coord.health.timeline.sample()

            status, body, _ = _req(base, "/internal/tenants")
            assert status == 200
            assert {"alpha", "bravo", "charlie", "mallory"} <= \
                set(body["tenants"])
            assert body["tenants"]["mallory"]["errors"] == 6

            rows = coord.health.slo.tenant_burn_rates()
            assert {r["tenant"] for r in rows} >= \
                {"alpha", "bravo", "charlie", "mallory"}
            assert [r["tenant"] for r in
                    coord.health.slo.tenant_alerting()] == ["mallory"]

            # burn gauges land in /metrics with tenant labels
            req = urllib.request.Request(base + "/metrics")
            with urllib.request.urlopen(req, timeout=10) as resp:
                text = resp.read().decode()
            assert "slo_burn_rate{" in text
            assert 'tenant="mallory"' in text

            # the timeline probe carries per-tenant rates (satellite 1)
            sample = coord.health.timeline.window(None)[-1]
            probe = sample["probes"]["tenants"]
            assert probe["enabled"] is True and probe["tracked"] >= 4

            triggers = [s["trigger"]
                        for s in coord.health.flight.summaries()]
            assert "tenant_burn" in triggers
