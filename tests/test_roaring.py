"""Roaring wire codec tests.

Roundtrip property tests plus hand-built binary fixtures constructed
byte-by-byte from the format spec (reference: roaring/roaring.go:19-50,
:1730 WriteTo) so the decoder is checked against the spec, not just
against our own encoder.
"""

import struct

import numpy as np
import pytest

from pilosa_tpu.storage import roaring as R


def test_roundtrip_mixed_containers(rng):
    # array (sparse), bitmap (dense), run (contiguous) in one blob
    sparse = np.sort(rng.choice(65536, 100, replace=False)).astype(np.uint64)
    dense = np.sort(rng.choice(65536, 30000, replace=False)).astype(np.uint64)
    run = np.arange(5000, 15000, dtype=np.uint64)
    pos = np.concatenate([
        sparse,                       # key 0
        (1 << 16) + dense,            # key 1
        (7 << 16) + run,              # key 7
    ])
    blob = R.encode_positions(pos)
    got = R.decode_to_positions(blob)
    np.testing.assert_array_equal(got, np.unique(pos))
    # container types chosen by size
    containers = R.decode(blob)
    assert set(containers) == {0, 1, 7}


def test_roundtrip_fuzz(rng):
    for _ in range(10):
        n = int(rng.integers(0, 5000))
        pos = rng.integers(0, 1 << 24, n, dtype=np.uint64)
        blob = R.encode_positions(pos)
        np.testing.assert_array_equal(
            R.decode_to_positions(blob), np.unique(pos))


def test_empty():
    blob = R.encode_positions([])
    assert R.decode_to_positions(blob).size == 0
    assert R.decode(blob) == {}


def _fixture(containers):
    """Build a pilosa-roaring blob straight from the spec."""
    n = len(containers)
    out = [struct.pack("<II", R.MAGIC, n)]
    headers, bodies = [], []
    for key, typ, vals in containers:
        if typ == R.TYPE_ARRAY:
            body = np.asarray(vals, "<u2").tobytes()
            card = len(vals)
        elif typ == R.TYPE_BITMAP:
            bits = np.zeros(1 << 16, np.uint8)
            bits[np.asarray(vals)] = 1
            body = np.packbits(bits, bitorder="little").tobytes()
            card = len(vals)
        else:
            runs = vals
            body = struct.pack("<H", len(runs)) + b"".join(
                struct.pack("<HH", a, b) for a, b in runs)
            card = sum(b - a + 1 for a, b in runs)
        headers.append(struct.pack("<QHH", key, typ, card - 1))
        bodies.append(body)
    out.extend(headers)
    off = 8 + 16 * n
    for body in bodies:
        out.append(struct.pack("<I", off))
        off += len(body)
    out.extend(bodies)
    return b"".join(out)


def test_decode_spec_fixture():
    blob = _fixture([
        (0, R.TYPE_ARRAY, [1, 5, 9]),
        (3, R.TYPE_RUN, [(10, 12), (100, 100)]),
        (2**40, R.TYPE_ARRAY, [65535]),
    ])
    got = R.decode(blob)
    np.testing.assert_array_equal(got[0], [1, 5, 9])
    np.testing.assert_array_equal(got[3], [10, 11, 12, 100])
    np.testing.assert_array_equal(got[2**40], [65535])
    pos = R.decode_to_positions(blob)
    assert int(pos[-1]) == (2**40 << 16) + 65535


def test_decode_bitmap_fixture():
    vals = list(range(0, 65536, 2))  # too dense for array
    blob = _fixture([(1, R.TYPE_BITMAP, vals)])
    np.testing.assert_array_equal(R.decode(blob)[1], vals)


def test_bad_inputs():
    with pytest.raises(R.RoaringError):
        R.decode(b"\x00")
    with pytest.raises(R.RoaringError):
        R.decode(struct.pack("<II", 99999, 0))
    # official-format magic (12346/12347) explicitly unsupported
    with pytest.raises(R.RoaringError):
        R.decode(struct.pack("<II", 12346, 0))
    # truncated container headers
    with pytest.raises(R.RoaringError):
        R.decode(struct.pack("<II", R.MAGIC, 5))


def test_encoder_picks_smallest():
    # contiguous run: run encoding beats array and bitmap
    blob = R.encode({0: np.arange(0, 10000, dtype=np.uint16)})
    containers = R.decode(blob)
    assert containers[0].size == 10000
    # blob should be tiny (one run)
    assert len(blob) < 64
    # random dense: bitmap (8KB) beats array (2 bytes/val over 4096)
    rng = np.random.default_rng(1)
    vals = np.sort(rng.choice(65536, 30000, replace=False)).astype(np.uint16)
    blob = R.encode({0: vals})
    assert len(blob) < 2 * 30000
