"""Star Schema Benchmark smoke: the tier-1 lane runs one query per
flight at tiny scale against the independent numpy oracle, on both the
semi-join plane and the hash fallback. The full 13-query battery
(plus 3-node cluster + faults + the >=2x p50 gate) lives in
``bench.py --configs 23``."""

import os

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.loadgen import ssb
from pilosa_tpu.sql import SQLEngine

SMOKE_FLIGHTS = ["Q1.1", "Q2.1", "Q3.1", "Q4.1"]


@pytest.fixture(scope="module")
def loaded():
    data = ssb.generate("tiny", seed=7)
    eng = SQLEngine(API())
    ssb.load(lambda q: eng.query(q), data)
    return data, eng


class TestSSBSmoke:
    @pytest.mark.parametrize("qid", SMOKE_FLIGHTS)
    def test_flight_vs_oracle(self, loaded, qid):
        data, eng = loaded
        got = eng.query(ssb.QUERIES[qid]).data
        assert ssb.verify(data, qid, got) is None
        os.environ["PILOSA_TPU_SEMIJOIN"] = "0"
        try:
            hashed = eng.query(ssb.QUERIES[qid]).data
        finally:
            del os.environ["PILOSA_TPU_SEMIJOIN"]
        assert got == hashed

    def test_all_queries_parse_and_plan(self, loaded):
        _, eng = loaded
        for qid, q in ssb.QUERIES.items():
            eng.query(q)  # no SQLError on any of the 13

    def test_datagen_deterministic(self):
        a = ssb.generate("tiny", seed=7)
        b = ssb.generate("tiny", seed=7)
        assert (a.lineorder["lo_revenue"] == b.lineorder["lo_revenue"]).all()
        assert a.part["p_brand1"] == b.part["p_brand1"]

    def test_full_battery(self, loaded):
        data, eng = loaded
        for qid, q in ssb.QUERIES.items():
            assert ssb.verify(data, qid, eng.query(q).data) is None
