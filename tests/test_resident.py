"""Device-residency plane (ISSUE 8): budget-charged resident stacks,
compiled per-family programs, and the warm path's observables.

The invariants are the acceptance criteria, not implementation echoes:
warm results bit-identical to the classic per-op path (the oracle the
bench compares against), warm traces free of ``stack.build`` /
``device.h2d_copy`` stages, StackStale from an evicted-then-stale
resident block retried transparently by the executor, and in-place
advance staying correct under concurrent writers with a budget tiny
enough to evict resident blocks mid-stream.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.core import FieldOptions, FieldType, Holder
from pilosa_tpu.core import stacked as stx
from pilosa_tpu.core.stacked import StackStale, stacked_set
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs import tracing as T
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.obs.tracing import TraceStore, Tracer
from pilosa_tpu.pql import Executor
from pilosa_tpu.pql import programs
from pilosa_tpu.shardwidth import SHARD_WIDTH

SHARDS = 2

# a query battery spanning every lowerable family plus the bail-out
# families (ConstRow/UnionRows/Shift run classic in both phases — they
# must *still* agree, proving the fallback composes)
QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(g=1)))",
    "Count(Union(Row(f=1), Row(g=2), Row(f=3)))",
    "Count(Difference(Row(f=1), Row(g=1)))",
    "Count(Xor(Row(f=1), Row(g=2)))",
    "Count(Not(Row(f=1)))",
    "Count(All())",
    "Count(Intersect(Row(v > 0), Row(f=1)))",
    "Count(Union(Row(v < 3), Row(g=2)))",
    "Intersect(Row(f=1), Row(g=1))",
    "Union(Row(f=2), Row(g=2))",
    "Difference(Not(Row(f=1)), Row(g=0))",
    "Count(UnionRows(Rows(f)))",
    "Count(Shift(Row(f=1), n=1))",
]


def _seed(h, rng):
    idx = h.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    idx.create_field("v", FieldOptions(type=FieldType.INT))
    f, g, v = idx.field("f"), idx.field("g"), idx.field("v")
    for s in range(SHARDS):
        base = s * SHARD_WIDTH
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 400))
        f.import_bits((cols % 5).tolist(), (base + cols).tolist())
        g.import_bits((cols % 3).tolist(), (base + cols).tolist())
        for c in cols[:50]:
            v.set_value(base + int(c), int(c % 7) - 3)
    return idx


@pytest.fixture
def env():
    h = Holder()
    e = Executor(h)
    _seed(h, np.random.default_rng(11))
    return h, e


@pytest.fixture
def tracer():
    prev = T.get_tracer()
    reg = MetricsRegistry()
    t = Tracer(enabled=True, sample_rate=1.0,
               store=TraceStore(64, registry=reg), registry=reg)
    T.set_tracer(t)
    yield t
    T.set_tracer(prev)


def _names(span_json, acc=None):
    acc = acc if acc is not None else []
    acc.append(span_json.get("name", ""))
    for c in span_json.get("children", ()):
        _names(c, acc)
    return acc


def _flat(results):
    out = []
    for r in results:
        out.append(r.columns if hasattr(r, "columns") else r)
    return out


class TestBitIdentity:
    def test_warm_programs_match_classic_path(self, env, monkeypatch):
        h, e = env
        monkeypatch.setattr(programs, "ENABLED", False)
        classic = [_flat(e.execute("i", q)) for q in QUERIES]
        # fresh stacks for the resident phase: identical inputs
        for fld in h.index("i").fields.values():
            fld._stacked_cache.clear()
        monkeypatch.setattr(programs, "ENABLED", True)
        warm = [_flat(e.execute("i", q)) for q in QUERIES]
        assert warm == classic
        # the lowerable families actually compiled programs
        assert programs.program_cache_len() > 0

    def test_masked_programs_match_classic_path(self, env, monkeypatch):
        """Superset fusion path: per-query shard masks over the fused
        layout must not perturb results."""
        h, e = env
        qs = ["Count(Row(f=1))", "Union(Row(f=1), Row(g=2))"]
        monkeypatch.setattr(programs, "ENABLED", False)
        classic = [
            _flat(r) for r in e.execute_many(
                "i", qs, per_query_shards=[[0], [0, 1]])]
        monkeypatch.setattr(programs, "ENABLED", True)
        warm = [
            _flat(r) for r in e.execute_many(
                "i", qs, per_query_shards=[[0], [0, 1]])]
        assert warm == classic

    def test_errors_identical_to_classic_path(self, env):
        from pilosa_tpu.pql.executor import PQLError

        h, e = env
        with pytest.raises(PQLError):
            e.execute("i", "Count(Intersect())")


class TestWarmTrace:
    def test_warm_query_has_no_staging_stage(self, env, tracer):
        h, e = env
        with tracer.start_trace("cold") as cold:
            e.execute("i", "Count(Intersect(Row(f=1), Row(g=1)))")
        cold_names = _names(cold.to_json())
        assert "stack.build" in cold_names
        assert "device.h2d_copy" in cold_names
        with tracer.start_trace("warm") as warm:
            e.execute("i", "Count(Intersect(Row(f=2), Row(g=2)))")
        warm_names = _names(warm.to_json())
        # same family, different rows: the compiled program and resident
        # planes serve it without touching the host
        assert "stack.build" not in warm_names
        assert "device.h2d_copy" not in warm_names

    def test_prewarm_makes_first_query_warm(self, tracer):
        h = Holder()
        e = Executor(h)
        _seed(h, np.random.default_rng(12))
        counts = h.prewarm("i")
        assert counts["set_stacks"] > 0 and counts["bsi_stacks"] > 0
        stats = h.residency_stats()
        assert stats["resident_bytes"] > 0
        assert stats["resident_bytes"] <= stats["budget_bytes"]
        with tracer.start_trace("q") as root:
            e.execute("i", "Count(Row(f=1))")
        assert "stack.build" not in _names(root.to_json())


class TestResidencyMetrics:
    def test_gauge_tracks_budget_and_hits_count(self, env):
        h, e = env
        e.execute("i", "Count(Row(f=1))")
        assert M.REGISTRY.value(M.METRIC_DEVICE_HBM_RESIDENT_BYTES) \
            == stx.BUDGET.used > 0
        hits0 = M.REGISTRY.value(M.METRIC_DEVICE_RESIDENT_HITS)
        e.execute("i", "Count(Row(f=2))")
        assert M.REGISTRY.value(M.METRIC_DEVICE_RESIDENT_HITS) > hits0

    def test_evictions_counted_under_tiny_budget(self, monkeypatch):
        monkeypatch.setattr(stx, "BUDGET", stx.DeviceBudget(1 << 20))
        ev0 = M.REGISTRY.value(M.METRIC_DEVICE_STACK_EVICTIONS)
        h = Holder()
        e = Executor(h)
        _seed(h, np.random.default_rng(13))
        for _ in range(2):
            for qsrc in ("Count(Row(f=1))", "Count(Row(g=1))",
                         "Count(Row(v > 0))"):
                e.execute("i", qsrc)
        assert M.REGISTRY.value(M.METRIC_DEVICE_STACK_EVICTIONS) > ev0
        assert M.REGISTRY.value(M.METRIC_DEVICE_HBM_RESIDENT_BYTES) \
            == stx.BUDGET.used


class TestStaleAndEviction:
    def test_evicted_resident_block_rebuilds_transparently(self, env):
        h, e = env
        want = e.execute("i", "Count(Row(f=1))")[0]
        f = h.index("i").field("f")
        st = stacked_set(f, [0, 1], "standard")
        assert not st.paged
        # simulate a budget eviction of the resident block mid-query
        # (exactly what DeviceBudget.charge's LRU pop does)
        st._drop_block(0)
        stx.BUDGET.release((st.serial, 0))
        assert e.execute("i", "Count(Row(f=1))")[0] == want

    def test_stale_evicted_block_raises_and_query_retries(self, env):
        h, e = env
        f = h.index("i").field("f")
        base = e.execute("i", "Count(Row(f=1))")[0]
        st = stacked_set(f, [0, 1], "standard")
        st._drop_block(0)
        stx.BUDGET.release((st.serial, 0))
        # a write past the snapshot makes the lazy rebuild stale: the
        # stack object must refuse to serve (StackStale), and the
        # executor-level read must retry against a fresh stack
        newcol = SHARD_WIDTH + 12345
        assert f.fragment(1).set_bit(1, newcol % SHARD_WIDTH)
        with pytest.raises(StackStale):
            st._ensure_block(0)
        assert e.execute("i", "Count(Row(f=1))")[0] == base + 1

    def test_bsi_resident_tensor_evicts_and_rebuilds(self, env):
        from pilosa_tpu.core.stacked import stacked_bsi

        h, e = env
        want = e.execute("i", "Count(Row(v > 0))")[0]
        v = h.index("i").field("v")
        st = stacked_bsi(v, [0, 1])
        st._drop()
        stx.BUDGET.release((st.serial, 0))
        assert st._planes is None
        assert e.execute("i", "Count(Row(v > 0))")[0] == want
        # evict, THEN write past the snapshot: the lazy rebuild must
        # refuse to serve and the executor must retry against fresh state
        st2 = stacked_bsi(v, [0, 1])
        st2._drop()
        stx.BUDGET.release((st2.serial, 0))
        v.set_value(SHARD_WIDTH + 777, 5)
        with pytest.raises(StackStale):
            _ = st2.planes
        assert e.execute("i", "Count(Row(v > 0))")[0] == want + 1


class TestConcurrentWritersTinyBudget:
    def test_in_place_advance_under_writers_and_eviction(self, monkeypatch):
        """Readers against resident stacks while writers advance them in
        place, under a budget small enough that resident blocks evict
        mid-query: every read must be internally consistent (count ==
        len(columns) of the same row) and the final state exact."""
        monkeypatch.setattr(stx, "BUDGET", stx.DeviceBudget(2 << 20))
        h = Holder()
        e = Executor(h)
        idx = h.create_index("i")
        idx.create_field("f")
        f = idx.field("f")
        rng = np.random.default_rng(17)
        cols0 = np.unique(rng.integers(0, SHARD_WIDTH, 200))
        f.import_bits([1] * len(cols0), cols0.tolist())
        e.execute("i", "Count(Row(f=1))")  # make the stack resident
        errors = []
        stop = threading.Event()
        written = list(range(SHARD_WIDTH, SHARD_WIDTH + 40))

        def writer():
            try:
                for c in written:
                    e.execute("i", f"Set({c}, f=1)")
            except Exception as exc:  # pragma: no cover
                errors.append(exc)
            finally:
                stop.set()

        def reader():
            try:
                prev = 0
                while not stop.is_set():
                    res = e.execute("i", "Count(Row(f=1)) Row(f=1)")
                    # writers only add bits and stack fetches only move
                    # forward in version, so counts are monotonic per
                    # reader and always bounded by seed/final state —
                    # a torn rebuild or lost in-place advance breaks this
                    assert len(cols0) <= res[0] <= len(cols0) + len(written)
                    assert res[0] >= prev
                    prev = res[0]
                    got = set(res[1].columns)
                    assert set(cols0.tolist()) <= got
                    assert got <= set(cols0.tolist()) | set(written)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        final = e.execute("i", "Row(f=1)")[0].columns
        assert final == sorted(set(cols0.tolist()) | set(written))
        assert e.execute("i", "Count(Row(f=1))")[0] == len(final)


class TestBoundedCaches:
    def test_device_zeros_shared_and_bounded(self):
        from pilosa_tpu.ops import bitmap as B

        a = B.device_zeros(64)
        assert B.device_zeros(64) is a  # shared, not per-executor
        for w in range(65, 65 + 2 * B._DEVICE_ZEROS_CAP):
            B.device_zeros(w)
        assert len(B._DEVICE_ZEROS) <= B._DEVICE_ZEROS_CAP

    def test_program_cache_bounded(self, env, monkeypatch):
        h, e = env
        monkeypatch.setattr(programs, "_PROGRAMS_CAP", 4)
        for n in range(1, 8):
            rows = ", ".join(f"Row(f={i % 5})" for i in range(n))
            e.execute("i", f"Count(Union({rows}))")
        assert programs.program_cache_len() <= 4
