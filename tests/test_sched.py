"""Query admission & micro-batching scheduler (pilosa_tpu/sched/).

All concurrency here is event-driven — pause()/resume() stage the queue,
ManualClock drives windows and deadlines — so the tests are deterministic
under JAX_PLATFORMS=cpu with no real-time sleeps.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.errors import AdmissionError, QueryDeadlineError
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.pql.result import result_to_json
from pilosa_tpu.sched import (
    ManualClock, PRIORITY_BATCH, QueryScheduler, group_key,
)
from pilosa_tpu.sched.batch import family_of
from pilosa_tpu.pql.parser import parse


class StubExecutor:
    """Records every execute(); each call's 'result' is its own PQL text,
    so scatter bugs (wrong offsets, swapped entries) surface as wrong
    strings."""

    def __init__(self, fail_when=None):
        self.calls = []
        self.fail_when = fail_when or (lambda q: False)
        self._lock = threading.Lock()

    def execute(self, index, query, shards=None):
        with self._lock:
            self.calls.append((index, [c.name for c in query.calls], shards))
        if self.fail_when(query):
            raise RuntimeError("stub failure")
        return [c.to_pql() for c in query.calls]


@pytest.fixture
def make_sched():
    created = []

    def make(executor, **kw):
        kw.setdefault("registry", MetricsRegistry())
        s = QueryScheduler(executor, **kw)
        created.append(s)
        return s

    yield make
    for s in created:
        s.close()


class TestGroupKey:
    def test_families(self):
        assert family_of(parse("Count(Row(f=1))")) == "count"
        assert family_of(parse("Intersect(Row(f=1), Row(g=2))")) == "bitmap"
        assert family_of(parse("Sum(field=v)")) == "agg"
        assert family_of(parse("TopN(f)")) == "rank"
        assert family_of(parse("Extract(All(), Rows(f))")) == "scan"
        # multi-call queries get a composite (order-insensitive) family
        two = parse("Count(Row(f=1))Row(g=2)")
        assert family_of(two) == "bitmap+count"

    def test_key_compatibility(self):
        q = parse("Count(Row(f=1))")
        assert group_key("i", q, [2, 1]) == group_key("i", q, [1, 2])
        assert group_key("i", q) != group_key("j", q)
        assert group_key("i", q) != group_key("i", parse("Row(f=1)"))


class TestBatching:
    def test_staged_queries_fuse_into_one_dispatch(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(8)]
        assert s.wait_queued(8) == 8
        s.resume()
        results = [h.result(timeout=5) for h in handles]
        # every caller got its OWN query's result back
        assert results == [[f"Count(Row(f={k}))"] for k in range(8)]
        assert len(stub.calls) == 1  # one fused dispatch
        assert stub.calls[0][1] == ["Count"] * 8

    def test_incompatible_shapes_split(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))")
        b = s.submit("i", "Row(f=1)")          # different family
        c = s.submit("j", "Count(Row(f=1))")   # different index
        assert s.wait_queued(3) == 3
        s.resume()
        for h in (a, b, c):
            h.result(timeout=5)
        assert len(stub.calls) == 3

    def test_max_batch_cap(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=3)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(7)]
        assert s.wait_queued(7) == 7
        s.resume()
        for h in handles:
            h.result(timeout=5)
        assert sorted(len(names) for _, names, _ in stub.calls) == [1, 3, 3]

    def test_window_fires_via_manual_clock(self, make_sched):
        stub = StubExecutor()
        clock = ManualClock()
        s = make_sched(stub, window_ms=5, max_batch=64, clock=clock)
        h = s.submit("i", "Count(Row(f=1))")
        assert s.wait_queued(1) == 1  # parked: window not elapsed
        assert not h.done()
        clock.advance(0.006)
        assert h.result(timeout=5) == ["Count(Row(f=1))"]

    def test_batch_size_cap_flushes_without_clock(self, make_sched):
        stub = StubExecutor()
        clock = ManualClock()  # time NEVER advances
        s = make_sched(stub, window_ms=1000, max_batch=2, clock=clock)
        a = s.submit("i", "Count(Row(f=1))")
        b = s.submit("i", "Count(Row(f=2))")
        # size cap alone must trigger the flush
        assert a.result(timeout=5) and b.result(timeout=5)


class TestAdmission:
    def test_queue_full_rejects_with_admission_error(self, make_sched):
        stub = StubExecutor()
        reg = MetricsRegistry()
        s = make_sched(stub, window_ms=0, max_queue=2, registry=reg)
        s.pause()
        s.submit("i", "Count(Row(f=1))")
        s.submit("i", "Count(Row(f=2))")
        with pytest.raises(AdmissionError):
            s.submit("i", "Count(Row(f=3))")
        assert reg.value(M.METRIC_SCHED_REJECTED, priority="interactive",
                         reason="queue_full") == 1
        s.resume()

    def test_batch_priority_has_tighter_limit(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_queue=4)
        s.pause()
        s.submit("i", "Count(Row(f=1))", priority=PRIORITY_BATCH)
        s.submit("i", "Count(Row(f=2))", priority=PRIORITY_BATCH)
        with pytest.raises(AdmissionError):  # batch capped at max_queue//2
            s.submit("i", "Count(Row(f=3))", priority=PRIORITY_BATCH)
        # interactive still has headroom up to max_queue
        s.submit("i", "Count(Row(f=4))")
        s.resume()

    def test_interactive_dispatches_before_batch(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        # batch-priority submitted FIRST, to a different group key
        b = s.submit("bulk", "Count(Row(f=1))", priority=PRIORITY_BATCH)
        a = s.submit("live", "Count(Row(f=1))")
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5)
        b.result(timeout=5)
        assert [c[0] for c in stub.calls] == ["live", "bulk"]

    def test_writes_refused(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0)
        with pytest.raises(ValueError):
            s.submit("i", "Set(1, f=2)")

    def test_execute_bypasses_queue_for_writes(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0)
        s.pause()  # queue frozen — a queued write would hang
        assert s.execute("i", "Set(1, f=2)") == ["Set(1, f=2)"]
        s.resume()

    def test_closed_scheduler_rejects(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0)
        s.close()
        with pytest.raises(AdmissionError):
            s.submit("i", "Count(Row(f=1))")

    def test_admit_ticket_bounds_inflight(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0, max_queue=1)
        with s.admit():
            with pytest.raises(AdmissionError):
                with s.admit():
                    pass
        with s.admit():  # released tickets free capacity
            pass


class TestDeadlines:
    def test_expired_deadline_fails_without_poisoning_batch(self, make_sched):
        stub = StubExecutor()
        reg = MetricsRegistry()
        clock = ManualClock()
        s = make_sched(stub, window_ms=0, clock=clock, registry=reg)
        s.pause()
        doomed = s.submit("i", "Count(Row(f=1))", deadline_ms=10)
        healthy = s.submit("i", "Count(Row(f=2))")
        assert s.wait_queued(2) == 2
        clock.advance(0.05)  # past doomed's deadline
        s.resume()
        assert healthy.result(timeout=5) == ["Count(Row(f=2))"]
        with pytest.raises(QueryDeadlineError):
            doomed.result(timeout=5)
        # the expired query never reached the executor
        assert stub.calls == [("i", ["Count"], None)]
        assert reg.value(M.METRIC_SCHED_DEADLINE_MISS,
                         priority="interactive") == 1

    def test_cancel_while_queued(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0)
        s.pause()
        victim = s.submit("i", "Count(Row(f=1))")
        other = s.submit("i", "Count(Row(f=2))")
        assert victim.cancel()
        s.resume()
        assert other.result(timeout=5) == ["Count(Row(f=2))"]
        with pytest.raises(QueryDeadlineError):
            victim.result(timeout=5)
        assert stub.calls == [("i", ["Count"], None)]


class TestErrorIsolation:
    def test_failing_batch_falls_back_to_solo_runs(self, make_sched):
        # the fused (multi-call) attempt fails; per-entry re-runs succeed
        stub = StubExecutor(fail_when=lambda q: len(q.calls) > 1)
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(3)]
        assert s.wait_queued(3) == 3
        s.resume()
        assert [h.result(timeout=5) for h in handles] == [
            [f"Count(Row(f={k}))"] for k in range(3)]
        assert len(stub.calls) == 4  # 1 failed fused + 3 solo

    def test_poison_query_fails_alone(self, make_sched):
        stub = StubExecutor(
            fail_when=lambda q: any("poison" in c.to_pql() for c in q.calls))
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        good = s.submit("i", "Count(Row(f=1))")
        bad = s.submit("i", "Count(Row(poison=1))")
        assert s.wait_queued(2) == 2
        s.resume()
        assert good.result(timeout=5) == ["Count(Row(f=1))"]
        with pytest.raises(RuntimeError):
            bad.result(timeout=5)


def _mixed_queries():
    return (["Count(Intersect(Row(city=%d), Row(device=%d)))" % (k % 5, k % 3)
             for k in range(8)]
            + ["Row(city=%d)" % (k % 5) for k in range(4)]
            + ["Intersect(Row(city=1), Row(device=2))",
               "Union(Row(city=0), Row(city=3))",
               "Count(Row(device=1))"])


@pytest.fixture(scope="module")
def parity_api():
    api = API()
    api.create_index("p")
    api.create_field("p", "city")
    api.create_field("p", "device")
    cols = list(range(300))
    api.import_bits("p", "city", rows=[c % 5 for c in cols], cols=cols)
    api.import_bits("p", "device", rows=[c % 3 for c in cols], cols=cols)
    return api


class TestParityWithSequential:
    def test_batched_results_bit_identical(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [result_to_json(api.query("p", q)[0]) for q in queries]

        sched = api.enable_scheduler(window_ms=0, max_batch=64)
        try:
            sched.pause()
            handles = [sched.submit("p", q) for q in queries]
            assert sched.wait_queued(len(queries)) == len(queries)
            sched.resume()
            got = [result_to_json(h.result(timeout=10)[0]) for h in handles]
        finally:
            api.disable_scheduler()
        assert got == want

    def test_concurrent_api_query_parity(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [result_to_json(api.query("p", q)[0]) for q in queries]
        api.enable_scheduler(window_ms=1.0, max_batch=64)
        try:
            with ThreadPoolExecutor(len(queries)) as pool:
                got = list(pool.map(
                    lambda q: result_to_json(api.query("p", q)[0]), queries))
        finally:
            api.disable_scheduler()
        assert got == want

    def test_execute_many_matches_execute(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [[result_to_json(r) for r in api.executor.execute("p", q)]
                for q in queries]
        many = api.executor.execute_many("p", queries)
        assert [[result_to_json(r) for r in rq] for rq in many] == want
        with pytest.raises(ValueError):
            api.executor.execute_many("p", ["Set(1, city=1)"])

    def test_sql_select_under_scheduler(self, parity_api):
        api = parity_api
        want = api.sql("SELECT COUNT(*) FROM p WHERE city = 1").data
        api.enable_scheduler(window_ms=0)
        try:
            got = api.sql("SELECT COUNT(*) FROM p WHERE city = 1").data
            # a held admission ticket exhausting max_queue=1... capacity
            # checks ride the same ticket the engine takes per SELECT
            with api.scheduler.admit():
                pass
        finally:
            api.disable_scheduler()
        assert got == want


class TestHTTPSurface:
    def test_429_on_full_queue_and_408_on_deadline(self, parity_api):
        import json
        import urllib.error
        import urllib.request

        from pilosa_tpu.server.http import serve

        api = parity_api
        clock = ManualClock()
        sched = api.enable_scheduler(window_ms=0, max_queue=1, clock=clock)
        srv, _ = serve(api, port=0, background=True)
        host, port = srv.server_address[:2]
        base = f"http://{host}:{port}"

        def post(path, body):
            req = urllib.request.Request(base + path, data=body.encode(),
                                         method="POST")
            req.add_header("Content-Type", "text/plain")
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        try:
            sched.pause()
            # first request parks in the (size-1) queue on a server thread
            fills = {}

            def fill():
                fills["r"] = post("/index/p/query?timeout_ms=10",
                                  "Count(Row(city=1))")

            t = threading.Thread(target=fill)
            t.start()
            assert sched.wait_queued(1) == 1
            code, body = post("/index/p/query", "Count(Row(city=2))")
            assert code == 429 and "full" in body["error"]
            # expire the parked query's deadline, then release the worker
            clock.advance(0.05)
            sched.resume()
            t.join(timeout=10)
            assert fills["r"][0] == 408
            # healthy path still serves through the scheduler
            code, body = post("/index/p/query", "Count(Row(city=1))")
            assert code == 200
        finally:
            api.disable_scheduler()
            srv.shutdown()
            srv.server_close()


class TestConfigSurface:
    def test_scheduler_config_fields(self):
        from pilosa_tpu.config import Config

        cfg = Config.from_sources(env={
            "PILOSA_TPU_SCHEDULER_ENABLED": "true",
            "PILOSA_TPU_SCHEDULER_WINDOW_MS": "2.5",
            "PILOSA_TPU_SCHEDULER_MAX_BATCH": "16",
            "PILOSA_TPU_SCHEDULER_MAX_QUEUE": "99",
        })
        assert cfg.scheduler_enabled is True
        assert cfg.scheduler_window_ms == 2.5
        assert cfg.scheduler_max_batch == 16
        assert cfg.scheduler_max_queue == 99

    def test_from_config_builder(self, make_sched):
        from pilosa_tpu.config import Config

        cfg = Config()
        cfg.scheduler_window_ms = 3.0
        cfg.scheduler_max_batch = 7
        s = QueryScheduler.from_config(StubExecutor(), cfg,
                                       registry=MetricsRegistry())
        try:
            assert s.window_s == 0.003
            assert s.max_batch == 7
        finally:
            s.close()

    def test_enable_disable_roundtrip(self):
        api = API()
        api.create_index("r")
        api.create_field("r", "f")
        api.enable_scheduler(window_ms=0)
        assert type(api.read_executor()).__name__ == "SchedulingExecutor"
        api.disable_scheduler()
        assert api.read_executor() is api.executor
        assert api.scheduler is None
