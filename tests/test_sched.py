"""Query admission & micro-batching scheduler (pilosa_tpu/sched/).

All concurrency here is event-driven — pause()/resume() stage the queue,
ManualClock drives windows and deadlines — so the tests are deterministic
under JAX_PLATFORMS=cpu with no real-time sleeps.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.errors import AdmissionError, QueryDeadlineError
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.pql.result import result_to_json
from pilosa_tpu.sched import (
    ManualClock, PRIORITY_BATCH, QueryScheduler, group_key,
)
from pilosa_tpu.sched.batch import family_of
from pilosa_tpu.pql.parser import parse


class StubExecutor:
    """Records every execute(); each call's 'result' is its own PQL text,
    so scatter bugs (wrong offsets, swapped entries) surface as wrong
    strings."""

    def __init__(self, fail_when=None):
        self.calls = []
        self.fail_when = fail_when or (lambda q: False)
        self._lock = threading.Lock()

    def execute(self, index, query, shards=None):
        with self._lock:
            self.calls.append((index, [c.name for c in query.calls], shards))
        if self.fail_when(query):
            raise RuntimeError("stub failure")
        return [c.to_pql() for c in query.calls]


@pytest.fixture
def make_sched():
    created = []

    def make(executor, **kw):
        kw.setdefault("registry", MetricsRegistry())
        s = QueryScheduler(executor, **kw)
        created.append(s)
        return s

    yield make
    for s in created:
        s.close()


class TestGroupKey:
    def test_families(self):
        assert family_of(parse("Count(Row(f=1))")) == "count"
        assert family_of(parse("Intersect(Row(f=1), Row(g=2))")) == "bitmap"
        assert family_of(parse("Sum(field=v)")) == "agg"
        assert family_of(parse("TopN(f)")) == "rank"
        assert family_of(parse("Extract(All(), Rows(f))")) == "scan"
        # multi-call queries get a composite (order-insensitive) family
        two = parse("Count(Row(f=1))Row(g=2)")
        assert family_of(two) == "bitmap+count"

    def test_key_compatibility(self):
        q = parse("Count(Row(f=1))")
        assert group_key("i", q, [2, 1]) == group_key("i", q, [1, 2])
        assert group_key("i", q) != group_key("j", q)
        assert group_key("i", q) != group_key("i", parse("Row(f=1)"))


class TestBatching:
    def test_staged_queries_fuse_into_one_dispatch(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(8)]
        assert s.wait_queued(8) == 8
        s.resume()
        results = [h.result(timeout=5) for h in handles]
        # every caller got its OWN query's result back
        assert results == [[f"Count(Row(f={k}))"] for k in range(8)]
        assert len(stub.calls) == 1  # one fused dispatch
        assert stub.calls[0][1] == ["Count"] * 8

    def test_incompatible_shapes_split(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))")
        b = s.submit("i", "Row(f=1)")          # different family
        c = s.submit("j", "Count(Row(f=1))")   # different index
        assert s.wait_queued(3) == 3
        s.resume()
        for h in (a, b, c):
            h.result(timeout=5)
        assert len(stub.calls) == 3

    def test_max_batch_cap(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=3)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(7)]
        assert s.wait_queued(7) == 7
        s.resume()
        for h in handles:
            h.result(timeout=5)
        assert sorted(len(names) for _, names, _ in stub.calls) == [1, 3, 3]

    def test_window_fires_via_manual_clock(self, make_sched):
        stub = StubExecutor()
        clock = ManualClock()
        s = make_sched(stub, window_ms=5, max_batch=64, clock=clock)
        h = s.submit("i", "Count(Row(f=1))")
        assert s.wait_queued(1) == 1  # parked: window not elapsed
        assert not h.done()
        clock.advance(0.006)
        assert h.result(timeout=5) == ["Count(Row(f=1))"]

    def test_batch_size_cap_flushes_without_clock(self, make_sched):
        stub = StubExecutor()
        clock = ManualClock()  # time NEVER advances
        s = make_sched(stub, window_ms=1000, max_batch=2, clock=clock)
        a = s.submit("i", "Count(Row(f=1))")
        b = s.submit("i", "Count(Row(f=2))")
        # size cap alone must trigger the flush
        assert a.result(timeout=5) and b.result(timeout=5)


class TestAdmission:
    def test_queue_full_rejects_with_admission_error(self, make_sched):
        stub = StubExecutor()
        reg = MetricsRegistry()
        s = make_sched(stub, window_ms=0, max_queue=2, registry=reg)
        s.pause()
        s.submit("i", "Count(Row(f=1))")
        s.submit("i", "Count(Row(f=2))")
        with pytest.raises(AdmissionError):
            s.submit("i", "Count(Row(f=3))")
        assert reg.value(M.METRIC_SCHED_REJECTED, priority="interactive",
                         reason="queue_full") == 1
        s.resume()

    def test_batch_priority_has_tighter_limit(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_queue=4)
        s.pause()
        s.submit("i", "Count(Row(f=1))", priority=PRIORITY_BATCH)
        s.submit("i", "Count(Row(f=2))", priority=PRIORITY_BATCH)
        with pytest.raises(AdmissionError):  # batch capped at max_queue//2
            s.submit("i", "Count(Row(f=3))", priority=PRIORITY_BATCH)
        # interactive still has headroom up to max_queue
        s.submit("i", "Count(Row(f=4))")
        s.resume()

    def test_interactive_dispatches_before_batch(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        # batch-priority submitted FIRST, to a different group key
        b = s.submit("bulk", "Count(Row(f=1))", priority=PRIORITY_BATCH)
        a = s.submit("live", "Count(Row(f=1))")
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5)
        b.result(timeout=5)
        assert [c[0] for c in stub.calls] == ["live", "bulk"]

    def test_writes_refused(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0)
        with pytest.raises(ValueError):
            s.submit("i", "Set(1, f=2)")

    def test_execute_bypasses_queue_for_writes(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0)
        s.pause()  # queue frozen — a queued write would hang
        assert s.execute("i", "Set(1, f=2)") == ["Set(1, f=2)"]
        s.resume()

    def test_closed_scheduler_rejects(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0)
        s.close()
        with pytest.raises(AdmissionError):
            s.submit("i", "Count(Row(f=1))")

    def test_admit_ticket_bounds_inflight(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0, max_queue=1)
        with s.admit():
            with pytest.raises(AdmissionError):
                with s.admit():
                    pass
        with s.admit():  # released tickets free capacity
            pass


class TestReadProtection:
    """Batch-priority admits (streaming-ingest applies) yield whenever
    interactive work is active: writes shed, reads keep the machine."""

    def test_batch_admit_yields_to_interactive_ticket(self, make_sched):
        clock = ManualClock()
        s = make_sched(StubExecutor(), window_ms=0, clock=clock)
        with s.admit():  # an interactive read is on the machine
            with pytest.raises(AdmissionError):
                with s.admit(priority=PRIORITY_BATCH):
                    pass
        # released, but the holdoff keeps batch work parked until reads
        # have been quiet long enough
        with pytest.raises(AdmissionError):
            with s.admit(priority=PRIORITY_BATCH):
                pass
        clock.advance(1.0)
        with s.admit(priority=PRIORITY_BATCH):
            pass

    def test_batch_admit_yields_to_queued_reads(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=0)
        s.pause()
        s.submit("i", "Count(Row(f=1))")
        with pytest.raises(AdmissionError):
            with s.admit(priority=PRIORITY_BATCH):
                pass
        s.resume()

    def test_yield_rejections_are_counted(self, make_sched):
        reg = MetricsRegistry()
        clock = ManualClock()
        s = make_sched(StubExecutor(), window_ms=0, clock=clock,
                       registry=reg)
        with s.admit():
            with pytest.raises(AdmissionError):
                with s.admit(priority=PRIORITY_BATCH):
                    pass
        assert reg.value(M.METRIC_SCHED_REJECTED, priority="batch",
                         reason="interactive_busy") == 1


class TestDeadlines:
    def test_expired_deadline_fails_without_poisoning_batch(self, make_sched):
        stub = StubExecutor()
        reg = MetricsRegistry()
        clock = ManualClock()
        s = make_sched(stub, window_ms=0, clock=clock, registry=reg)
        s.pause()
        doomed = s.submit("i", "Count(Row(f=1))", deadline_ms=10)
        healthy = s.submit("i", "Count(Row(f=2))")
        assert s.wait_queued(2) == 2
        clock.advance(0.05)  # past doomed's deadline
        s.resume()
        assert healthy.result(timeout=5) == ["Count(Row(f=2))"]
        with pytest.raises(QueryDeadlineError):
            doomed.result(timeout=5)
        # the expired query never reached the executor
        assert stub.calls == [("i", ["Count"], None)]
        assert reg.value(M.METRIC_SCHED_DEADLINE_MISS,
                         priority="interactive") == 1

    def test_cancel_while_queued(self, make_sched):
        stub = StubExecutor()
        s = make_sched(stub, window_ms=0)
        s.pause()
        victim = s.submit("i", "Count(Row(f=1))")
        other = s.submit("i", "Count(Row(f=2))")
        assert victim.cancel()
        s.resume()
        assert other.result(timeout=5) == ["Count(Row(f=2))"]
        with pytest.raises(QueryDeadlineError):
            victim.result(timeout=5)
        assert stub.calls == [("i", ["Count"], None)]


class TestErrorIsolation:
    def test_failing_batch_falls_back_to_solo_runs(self, make_sched):
        # the fused (multi-call) attempt fails; per-entry re-runs succeed
        stub = StubExecutor(fail_when=lambda q: len(q.calls) > 1)
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))") for k in range(3)]
        assert s.wait_queued(3) == 3
        s.resume()
        assert [h.result(timeout=5) for h in handles] == [
            [f"Count(Row(f={k}))"] for k in range(3)]
        assert len(stub.calls) == 4  # 1 failed fused + 3 solo

    def test_poison_query_fails_alone(self, make_sched):
        stub = StubExecutor(
            fail_when=lambda q: any("poison" in c.to_pql() for c in q.calls))
        s = make_sched(stub, window_ms=0, max_batch=64)
        s.pause()
        good = s.submit("i", "Count(Row(f=1))")
        bad = s.submit("i", "Count(Row(poison=1))")
        assert s.wait_queued(2) == 2
        s.resume()
        assert good.result(timeout=5) == ["Count(Row(f=1))"]
        with pytest.raises(RuntimeError):
            bad.result(timeout=5)


def _mixed_queries():
    return (["Count(Intersect(Row(city=%d), Row(device=%d)))" % (k % 5, k % 3)
             for k in range(8)]
            + ["Row(city=%d)" % (k % 5) for k in range(4)]
            + ["Intersect(Row(city=1), Row(device=2))",
               "Union(Row(city=0), Row(city=3))",
               "Count(Row(device=1))"])


@pytest.fixture(scope="module")
def parity_api():
    api = API()
    api.create_index("p")
    api.create_field("p", "city")
    api.create_field("p", "device")
    cols = list(range(300))
    api.import_bits("p", "city", rows=[c % 5 for c in cols], cols=cols)
    api.import_bits("p", "device", rows=[c % 3 for c in cols], cols=cols)
    return api


class TestParityWithSequential:
    def test_batched_results_bit_identical(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [result_to_json(api.query("p", q)[0]) for q in queries]

        sched = api.enable_scheduler(window_ms=0, max_batch=64)
        try:
            sched.pause()
            handles = [sched.submit("p", q) for q in queries]
            assert sched.wait_queued(len(queries)) == len(queries)
            sched.resume()
            got = [result_to_json(h.result(timeout=10)[0]) for h in handles]
        finally:
            api.disable_scheduler()
        assert got == want

    def test_concurrent_api_query_parity(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [result_to_json(api.query("p", q)[0]) for q in queries]
        api.enable_scheduler(window_ms=1.0, max_batch=64)
        try:
            with ThreadPoolExecutor(len(queries)) as pool:
                got = list(pool.map(
                    lambda q: result_to_json(api.query("p", q)[0]), queries))
        finally:
            api.disable_scheduler()
        assert got == want

    def test_execute_many_matches_execute(self, parity_api):
        api = parity_api
        queries = _mixed_queries()
        want = [[result_to_json(r) for r in api.executor.execute("p", q)]
                for q in queries]
        many = api.executor.execute_many("p", queries)
        assert [[result_to_json(r) for r in rq] for rq in many] == want
        with pytest.raises(ValueError):
            api.executor.execute_many("p", ["Set(1, city=1)"])

    def test_sql_select_under_scheduler(self, parity_api):
        api = parity_api
        want = api.sql("SELECT COUNT(*) FROM p WHERE city = 1").data
        api.enable_scheduler(window_ms=0)
        try:
            got = api.sql("SELECT COUNT(*) FROM p WHERE city = 1").data
            # a held admission ticket exhausting max_queue=1... capacity
            # checks ride the same ticket the engine takes per SELECT
            with api.scheduler.admit():
                pass
        finally:
            api.disable_scheduler()
        assert got == want


class TestHTTPSurface:
    def test_429_on_full_queue_and_408_on_deadline(self, parity_api):
        import json
        import urllib.error
        import urllib.request

        from pilosa_tpu.server.http import serve

        api = parity_api
        clock = ManualClock()
        sched = api.enable_scheduler(window_ms=0, max_queue=1, clock=clock)
        srv, _ = serve(api, port=0, background=True)
        host, port = srv.server_address[:2]
        base = f"http://{host}:{port}"

        def post(path, body):
            req = urllib.request.Request(base + path, data=body.encode(),
                                         method="POST")
            req.add_header("Content-Type", "text/plain")
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        try:
            sched.pause()
            # first request parks in the (size-1) queue on a server thread
            fills = {}

            def fill():
                fills["r"] = post("/index/p/query?timeout_ms=10",
                                  "Count(Row(city=1))")

            t = threading.Thread(target=fill)
            t.start()
            assert sched.wait_queued(1) == 1
            code, body = post("/index/p/query", "Count(Row(city=2))")
            assert code == 429 and "full" in body["error"]
            # expire the parked query's deadline, then release the worker
            clock.advance(0.05)
            sched.resume()
            t.join(timeout=10)
            assert fills["r"][0] == 408
            # healthy path still serves through the scheduler
            code, body = post("/index/p/query", "Count(Row(city=1))")
            assert code == 200
        finally:
            api.disable_scheduler()
            srv.shutdown()
            srv.server_close()


class TestConfigSurface:
    def test_scheduler_config_fields(self):
        from pilosa_tpu.config import Config

        cfg = Config.from_sources(env={
            "PILOSA_TPU_SCHEDULER_ENABLED": "true",
            "PILOSA_TPU_SCHEDULER_WINDOW_MS": "2.5",
            "PILOSA_TPU_SCHEDULER_MAX_BATCH": "16",
            "PILOSA_TPU_SCHEDULER_MAX_QUEUE": "99",
        })
        assert cfg.scheduler_enabled is True
        assert cfg.scheduler_window_ms == 2.5
        assert cfg.scheduler_max_batch == 16
        assert cfg.scheduler_max_queue == 99

    def test_from_config_builder(self, make_sched):
        from pilosa_tpu.config import Config

        cfg = Config()
        cfg.scheduler_window_ms = 3.0
        cfg.scheduler_max_batch = 7
        s = QueryScheduler.from_config(StubExecutor(), cfg,
                                       registry=MetricsRegistry())
        try:
            assert s.window_s == 0.003
            assert s.max_batch == 7
        finally:
            s.close()

    def test_enable_disable_roundtrip(self):
        api = API()
        api.create_index("r")
        api.create_field("r", "f")
        api.enable_scheduler(window_ms=0)
        assert type(api.read_executor()).__name__ == "SchedulingExecutor"
        api.disable_scheduler()
        assert api.read_executor() is api.executor
        assert api.scheduler is None


class StubFusionExecutor(StubExecutor):
    """StubExecutor advertising masked superset execution. Records the
    per_query_shards each fused dispatch received, so merge decisions
    (who joined, in what order) are directly observable."""

    supports_shard_masks = True

    def execute_many(self, index, queries, shards=None,
                     per_query_shards=None):
        with self._lock:
            self.calls.append((
                index, [[c.name for c in q.calls] for q in queries],
                shards if per_query_shards is None
                else list(per_query_shards)))
        if any(self.fail_when(q) for q in queries):
            raise RuntimeError("stub failure")
        return [[c.to_pql() for c in q.calls] for q in queries]


class TestSupersetFusion:
    def test_overlapping_shard_sets_merge_into_one_dispatch(self, make_sched):
        stub = StubFusionExecutor()
        reg = MetricsRegistry()
        s = make_sched(stub, window_ms=0, max_batch=64,
                       fuse_waste_ratio=2.0, registry=reg)
        s.pause()
        handles = [
            s.submit("i", "Count(Row(f=1))", shards=[0, 1, 2, 3]),
            s.submit("i", "Count(Row(f=2))", shards=[2, 3, 4, 5]),
            s.submit("i", "Count(Row(f=3))", shards=[4, 5, 6, 7]),
        ]
        assert s.wait_queued(3) == 3
        s.resume()
        results = [h.result(timeout=5) for h in handles]
        assert results == [[f"Count(Row(f={k}))"] for k in (1, 2, 3)]
        assert len(stub.calls) == 1  # ONE fused dispatch across 3 sets
        _, _, per_q = stub.calls[0]
        assert per_q == [(0, 1, 2, 3), (2, 3, 4, 5), (4, 5, 6, 7)]
        assert reg.value(M.METRIC_SCHED_SUPERSET_MERGES, family="count") == 2
        assert reg.value(M.METRIC_SCHED_FUSED_QUERIES, family="count") == 3
        assert reg.value(M.METRIC_SCHED_BATCHES, family="count") == 1

    def test_waste_ratio_gates_merging(self, make_sched):
        stub = StubFusionExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64, fuse_waste_ratio=1.5)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))", shards=[0, 1])
        b = s.submit("i", "Count(Row(f=2))", shards=[2, 3])  # union 4 > 1.5*2
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5), b.result(timeout=5)
        assert len(stub.calls) == 2  # padding budget refused the merge

    def test_zero_ratio_disables_fusion(self, make_sched):
        stub = StubFusionExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64, fuse_waste_ratio=0)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))", shards=[0, 1])
        b = s.submit("i", "Count(Row(f=2))", shards=[0, 1, 2])
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5), b.result(timeout=5)
        assert len(stub.calls) == 2

    def test_executor_without_masks_never_merges(self, make_sched):
        stub = StubExecutor()  # no supports_shard_masks / execute_many
        s = make_sched(stub, window_ms=0, max_batch=64, fuse_waste_ratio=8.0)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))", shards=[0, 1])
        b = s.submit("i", "Count(Row(f=2))", shards=[1, 2])
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5), b.result(timeout=5)
        assert len(stub.calls) == 2

    def test_scan_family_and_none_shards_excluded(self, make_sched):
        stub = StubFusionExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64, fuse_waste_ratio=8.0)
        s.pause()
        a = s.submit("i", "Extract(All(), Rows(f))", shards=[0, 1])
        b = s.submit("i", "Extract(All(), Rows(f))", shards=[1, 2])
        c = s.submit("i", "Count(Row(f=1))")  # None = all-shards group
        d = s.submit("i", "Count(Row(f=2))", shards=[0, 1])
        assert s.wait_queued(4) == 4
        s.resume()
        for h in (a, b, c, d):
            h.result(timeout=5)
        # scan queries and the None-shards group each dispatch apart
        assert len(stub.calls) == 4

    def test_options_shards_override_not_fused(self, make_sched):
        stub = StubFusionExecutor()
        s = make_sched(stub, window_ms=0, max_batch=64, fuse_waste_ratio=8.0)
        s.pause()
        a = s.submit("i", "Count(Row(f=1))", shards=[0, 1])
        b = s.submit("i", "Options(Count(Row(f=2)), shards=[9])",
                     shards=[1, 2])
        assert s.wait_queued(2) == 2
        s.resume()
        a.result(timeout=5), b.result(timeout=5)
        # the per-call override re-scopes the read; it must keep its own
        # dispatch rather than execute under a union-sized mask
        assert len(stub.calls) == 2

    def test_merge_respects_max_batch(self, make_sched):
        stub = StubFusionExecutor()
        s = make_sched(stub, window_ms=0, max_batch=2, fuse_waste_ratio=8.0)
        s.pause()
        handles = [s.submit("i", f"Count(Row(f={k}))", shards=[k, k + 1])
                   for k in range(3)]
        assert s.wait_queued(3) == 3
        s.resume()
        for h in handles:
            h.result(timeout=5)
        assert sorted(len(qs) for _, qs, _ in stub.calls) == [1, 2]

    def test_merged_candidate_cancel_and_deadline_honored(self, make_sched):
        stub = StubFusionExecutor()
        clock = ManualClock()
        s = make_sched(stub, window_ms=0, max_batch=64,
                       fuse_waste_ratio=8.0, clock=clock)
        s.pause()
        lead = s.submit("i", "Count(Row(f=1))", shards=[0, 1])
        doomed = s.submit("i", "Count(Row(f=2))", shards=[1, 2],
                          deadline_ms=10)
        gone = s.submit("i", "Count(Row(f=3))", shards=[2, 3])
        ok = s.submit("i", "Count(Row(f=4))", shards=[3, 4])
        assert s.wait_queued(4) == 4
        assert gone.cancel()
        clock.advance(0.05)  # past doomed's deadline
        s.resume()
        assert lead.result(timeout=5) == ["Count(Row(f=1))"]
        assert ok.result(timeout=5) == ["Count(Row(f=4))"]
        with pytest.raises(QueryDeadlineError):
            doomed.result(timeout=5)
        with pytest.raises(QueryDeadlineError):
            gone.result(timeout=5)
        # one dispatch; only the live entries reached the executor
        assert len(stub.calls) == 1
        assert stub.calls[0][2] == [(0, 1), (3, 4)]

    def test_fused_results_bit_identical_to_sequential(self, parity_api):
        api = parity_api
        shards = [0]  # the 300-col fixture lives entirely in shard 0
        queries = _mixed_queries()
        want = [result_to_json(api.query("p", q, shards=shards)[0])
                for q in queries]
        reg = MetricsRegistry()
        sched = api.enable_scheduler(window_ms=0, max_batch=64,
                                     fuse_waste_ratio=8.0, registry=reg)
        try:
            sched.pause()
            handles = [sched.submit("p", q, shards=shards) for q in queries]
            assert sched.wait_queued(len(queries)) == len(queries)
            sched.resume()
            got = [result_to_json(h.result(timeout=10)[0]) for h in handles]
        finally:
            api.disable_scheduler()
        assert got == want


class TestAdaptiveWindow:
    def test_disabled_by_default(self, make_sched):
        s = make_sched(StubExecutor(), window_ms=3)
        assert s.adaptive_window is False
        assert s.current_window_ms() == 3.0

    def test_idle_stream_collapses_to_min(self, make_sched):
        clock = ManualClock()
        s = make_sched(StubExecutor(), adaptive_window=True,
                       window_min_ms=1, window_max_ms=100, max_batch=10,
                       clock=clock)
        s.pause()
        # arrivals 10s apart: no batch will ever fill; don't hold anyone
        for k in range(4):
            s.submit("i", f"Count(Row(f={k}))")
            clock.advance(10.0)
        assert s.current_window_ms() == 1.0
        s.resume()

    def test_burst_earns_full_window(self, make_sched):
        clock = ManualClock()
        s = make_sched(StubExecutor(), adaptive_window=True,
                       window_min_ms=1, window_max_ms=100, max_batch=10,
                       clock=clock)
        s.pause()
        # 1ms gaps: a 10-query batch fills well inside window_max
        for k in range(8):
            s.submit("i", f"Count(Row(f={k}))")
            clock.advance(0.001)
        assert s.current_window_ms() == 100.0
        s.resume()

    def test_window_tracks_load_shift(self, make_sched):
        clock = ManualClock()
        reg = MetricsRegistry()
        s = make_sched(StubExecutor(), adaptive_window=True,
                       window_min_ms=1, window_max_ms=100, max_batch=10,
                       clock=clock, registry=reg)
        s.pause()
        for k in range(8):
            s.submit("i", f"Count(Row(f={k}))")
            clock.advance(0.001)
        busy = s.current_window_ms()
        for k in range(20):
            s.submit("i", f"Count(Row(g={k}))")
            clock.advance(5.0)
        idle = s.current_window_ms()
        assert busy > idle
        assert reg.value(M.METRIC_SCHED_WINDOW_MS) == idle
        s.resume()

    def test_from_config_carries_adaptive_fields(self):
        from pilosa_tpu.config import Config

        cfg = Config.from_sources(env={
            "PILOSA_TPU_SCHEDULER_FUSE_WASTE_RATIO": "3.5",
            "PILOSA_TPU_SCHEDULER_ADAPTIVE_WINDOW": "true",
            "PILOSA_TPU_SCHEDULER_WINDOW_MIN_MS": "0.5",
            "PILOSA_TPU_SCHEDULER_WINDOW_MAX_MS": "9",
        })
        assert cfg.scheduler_fuse_waste_ratio == 3.5
        assert cfg.scheduler_adaptive_window is True
        assert cfg.scheduler_window_min_ms == 0.5
        assert cfg.scheduler_window_max_ms == 9.0
        s = QueryScheduler.from_config(StubFusionExecutor(), cfg,
                                       registry=MetricsRegistry())
        try:
            assert s.fuse_waste_ratio == 3.5
            assert s.adaptive_window is True
            assert s.window_min_s == 0.0005
            assert s.window_max_s == 0.009
        finally:
            s.close()


class TestFamilyClassification:
    """family_of / fusibility must agree with the executor's maskability
    (regression: Options unwrapping is now shared via pql/ast.py)."""

    def test_family_unwraps_nested_options(self):
        from pilosa_tpu.pql.ast import Call, Query

        inner = parse("Count(Row(f=1))").calls[0]
        wrapped = Query([Call("Options", {"shards": [0]}, [
            Call("Options", {}, [inner])])])
        assert family_of(wrapped) == "count"

    def test_fusible_families(self):
        from pilosa_tpu.sched.batch import fusible_family

        assert fusible_family("count")
        assert fusible_family("agg+bitmap")
        assert not fusible_family("scan")
        assert not fusible_family("count+scan")

    def test_options_shards_blocks_maskability_not_family(self):
        from pilosa_tpu.pql.executor import query_maskable

        plain = parse("Options(Count(Row(f=1)), exclude=true)")
        scoped = parse("Options(Count(Row(f=1)), shards=[0])")
        assert family_of(plain) == family_of(scoped) == "count"
        assert query_maskable(plain)
        assert not query_maskable(scoped)
