"""Observability + ops subsystems: metrics, tracing, query history,
cluster transactions, TTL view removal, mutex check.

Reference analogs: metrics.go names, tracing/tracing.go span trees,
tracker.go history ring, transaction.go exclusive semantics
(transaction_test.go), server.go ViewsRemoval, view.go:449 mutexCheck.
"""

import datetime as dt
import json
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.obs.metrics import MetricsRegistry, REGISTRY
from pilosa_tpu.obs.tracing import Tracer
from pilosa_tpu.server.maintenance import mutex_check, remove_expired_views
from pilosa_tpu.transaction import TransactionError, TransactionManager


class TestMetrics:
    def test_counters_gauges_summaries(self):
        r = MetricsRegistry()
        r.count("pql_queries_total")
        r.count("pql_queries_total", 2)
        r.gauge("maximum_shard", 5, index="i")
        r.observe("http_request_duration_seconds", 0.25, route="q")
        assert r.value("pql_queries_total") == 3
        text = r.prometheus_text()
        assert "pilosa_pql_queries_total 3" in text
        assert 'pilosa_maximum_shard{index="i"} 5' in text
        assert 'pilosa_http_request_duration_seconds_count{route="q"} 1' in text

    def test_bucketed_histograms(self):
        r = MetricsRegistry()
        buckets = (1.0, 2.0, 4.0)
        for v in (1, 1, 3, 100):
            r.observe_bucketed("sched_batch_size", v, buckets,
                               family="count")
        text = r.prometheus_text()
        # cumulative counts, le formatted last after sorted labels
        assert "# TYPE pilosa_sched_batch_size histogram" in text
        assert 'pilosa_sched_batch_size_bucket{family="count",le="1"} 2' \
            in text
        assert 'pilosa_sched_batch_size_bucket{family="count",le="2"} 2' \
            in text
        assert 'pilosa_sched_batch_size_bucket{family="count",le="4"} 3' \
            in text
        assert 'pilosa_sched_batch_size_bucket{family="count",le="+Inf"} 4' \
            in text
        assert 'pilosa_sched_batch_size_sum{family="count"} 105' in text
        assert 'pilosa_sched_batch_size_count{family="count"} 4' in text
        j = r.as_json()["histograms"]['sched_batch_size{family="count"}']
        assert j["buckets"] == {"1": 2, "2": 0, "4": 1}
        assert j["overflow"] == 1
        assert j["count"] == 4
        snap = r.histogram("sched_batch_size", family="count")
        assert snap["count"] == 4 and snap["sum"] == 105

    def test_scheduler_metrics_flow_through_exposition(self):
        from pilosa_tpu.api import API as _API
        from pilosa_tpu.obs import metrics as M

        r = MetricsRegistry()
        api = _API()
        api.create_index("sm")
        api.create_field("sm", "f")
        api.query("sm", "Set(1, f=1)Set(2, f=1)")
        sched = api.enable_scheduler(window_ms=0, registry=r)
        try:
            sched.pause()
            hs = [sched.submit("sm", "Count(Row(f=1))") for _ in range(3)]
            assert sched.wait_queued(3) == 3
            sched.resume()
            for h in hs:
                assert h.result(timeout=5) == [2]
        finally:
            api.disable_scheduler()
        assert r.value(M.METRIC_SCHED_QUERIES, family="count") == 3
        assert r.value(M.METRIC_SCHED_BATCHES, family="count") == 1
        text = r.prometheus_text()
        assert 'pilosa_sched_batch_size_bucket{family="count",le="4"} 1' \
            in text
        assert 'pilosa_sched_queries_total{family="count"} 3' in text
        assert "pilosa_sched_batch_wait_seconds_count" in text
        assert "pilosa_sched_amortized_dispatch_seconds_sum" in text
        j = r.as_json()
        assert 'sched_batch_size{family="count"}' in j["histograms"]

    def test_cache_metrics_flow_through_exposition(self):
        from pilosa_tpu.api import API as _API
        from pilosa_tpu.obs import metrics as M

        r = MetricsRegistry()
        api = _API()
        api.create_index("cm")
        api.create_field("cm", "f")
        api.query("cm", "Set(1, f=1)Set(2, f=1)")
        api.enable_cache(max_entries=1, registry=r)
        api.query("cm", "Count(Row(f=1))")  # miss + insert
        api.query("cm", "Count(Row(f=1))")  # hit
        api.query("cm", "Row(f=1)")  # miss, evicts the Count entry
        api.query("cm", "Options(Row(f=1), shards=[0])")  # bypass
        api.disable_cache()
        assert r.value(M.METRIC_CACHE_HITS) == 1
        assert r.value(M.METRIC_CACHE_MISSES) == 2
        assert r.value(M.METRIC_CACHE_BYPASS) == 1
        assert r.value(M.METRIC_CACHE_EVICTIONS, reason="entries") == 1
        assert r.value(M.METRIC_CACHE_ENTRIES) == 1
        assert r.value(M.METRIC_CACHE_BYTES) > 0
        text = r.prometheus_text()
        assert "pilosa_cache_hits_total 1" in text
        assert "pilosa_cache_misses_total 2" in text
        assert "pilosa_cache_bypass_total 1" in text
        assert 'pilosa_cache_evictions_total{reason="entries"} 1' in text
        assert "# TYPE pilosa_cache_resident_bytes gauge" in text
        # both latency histograms expose the shared bucket layout
        assert "# TYPE pilosa_cache_hit_seconds histogram" in text
        assert 'pilosa_cache_hit_seconds_bucket{le="+Inf"} 1' in text
        assert "# TYPE pilosa_cache_dispatch_seconds histogram" in text
        assert "pilosa_cache_dispatch_seconds_count 2" in text
        j = r.as_json()
        assert "cache_hit_seconds" in j["histograms"]
        assert "cache_dispatch_seconds" in j["histograms"]
        assert j["counters"]["cache_hits_total"] == 1

    def test_api_instruments(self):
        base = REGISTRY.value("pql_queries_total")
        api = API()
        api.create_index("m")
        api.create_field("m", "f")
        api.query("m", "Set(1, f=1)")
        api.import_bits("m", "f", rows=[1], cols=[2])
        assert REGISTRY.value("pql_queries_total") == base + 1
        assert REGISTRY.value("imported_total") >= 1
        assert REGISTRY.value("maximum_shard", index="m") == 0


class TestTracing:
    def test_span_tree(self):
        t = Tracer()
        # roots are explicit now (start_trace); start_span outside any
        # trace is a NOP so background work never creates stray traces
        with t.start_trace("root") as root:
            with t.start_span("child", shard=3):
                pass
            with t.start_span("child2"):
                pass
        j = root.to_json()
        assert j["name"] == "root"
        assert [c["name"] for c in j["children"]] == ["child", "child2"]
        assert j["children"][0]["tags"] == {"shard": 3}
        assert j["duration_ns"] > 0


class TestHistory:
    def test_ring_records_pql_and_sql(self):
        api = API()
        api.create_index("h")
        api.create_field("h", "f")
        api.query("h", "Count(Row(f=1))")
        api.sql("show tables")
        hist = api.history.list()
        assert hist[0].language == "sql" and hist[0].status == "complete"
        assert hist[1].language == "pql" and hist[1].query == "Count(Row(f=1))"
        with pytest.raises(Exception):
            api.query("h", "Bogus()")
        assert api.history.list()[0].status == "error"

    def test_sql_system_tables(self):
        api = API()
        api.create_index("h")
        api.create_field("h", "f")
        api.query("h", "Count(Row(f=1))")
        res = api.sql("select query, status from fb_exec_requests")
        assert ["Count(Row(f=1))", "complete"] in res.data
        res = api.sql("select * from fb_performance_counters")
        assert any(row[0].startswith("pql_queries_total") for row in res.data)


class TestTransactions:
    def test_exclusive_blocks_others(self):
        tm = TransactionManager()
        t1 = tm.start("a")
        assert t1.active and not t1.exclusive
        tex = tm.start("x", exclusive=True)
        assert not tex.active  # pending until alone
        with pytest.raises(TransactionError):
            tm.start("b")  # blocked while exclusive exists
        tm.finish("a")
        assert tm.get("x").active  # activated once alone
        assert tm.exclusive_active()
        tm.finish("x")
        assert tm.list() == []

    def test_deadline_expiry(self):
        tm = TransactionManager()
        tm.start("t", timeout_s=-1)  # already expired
        with pytest.raises(TransactionError):
            tm.get("t")

    def test_http_endpoints(self):
        from pilosa_tpu.server.http import serve

        api = API()
        srv, _ = serve(api, port=0, background=True)
        port = srv.server_address[1]

        def req(method, path, body=None):
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(body).encode() if body is not None else None,
                method=method)
            return json.loads(urllib.request.urlopen(r).read())

        tx = req("POST", "/transaction", {"id": "backup", "exclusive": True})
        assert tx["transaction"]["active"]
        got = req("GET", "/transaction/backup")
        assert got["transaction"]["exclusive"]
        assert len(req("GET", "/transactions")["transactions"]) == 1
        req("POST", "/transaction/backup/finish")
        assert req("GET", "/transactions")["transactions"] == []
        # metrics + history endpoints
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "pilosa_transaction_start" in text
        assert isinstance(req("GET", "/query-history"), list)
        srv.shutdown()


class TestTTLRemoval:
    def test_expired_views_removed(self):
        api = API()
        api.create_index("t")
        api.create_field("t", "ev", {"type": "time", "timeQuantum": "YMD",
                                     "ttl": 30 * 86400})
        api.query("t", "Set(1, ev=5, 2020-01-02T00:00)")
        api.query("t", "Set(2, ev=5, 2099-06-01T00:00)")
        field = api.holder.index("t").field("ev")
        before = set(field.views)
        removed = remove_expired_views(api.holder,
                                       now=dt.datetime(2099, 6, 2))
        assert any("standard_2020" in r for r in removed)
        assert all("standard_2099" not in r for r in removed)
        # standard view unaffected; recent views kept
        assert "standard" in field.views
        assert any(v.startswith("standard_2099") for v in field.views)
        assert set(field.views) < before

    def test_no_ttl_untouched(self):
        api = API()
        api.create_index("t")
        api.create_field("t", "ev", {"type": "time", "timeQuantum": "YMD"})
        api.query("t", "Set(1, ev=5, 2020-01-02T00:00)")
        assert remove_expired_views(api.holder,
                                    now=dt.datetime(2099, 1, 1)) == []


class TestMutexCheck:
    def test_detects_violation(self):
        api = API()
        api.create_index("m")
        api.create_field("m", "mx", {"type": "mutex"})
        api.query("m", "Set(5, mx=1)")
        # violate the invariant behind the field API's back
        field = api.holder.index("m").field("mx")
        frag = field.fragment(0)
        frag.set_bit(frag.row_ids[0] + 1 if 2 not in frag.row_index else 3, 5)
        out = mutex_check(api.holder, "m")
        assert "mx" in out and 5 in out["mx"] and len(out["mx"][5]) == 2

    def test_clean(self):
        api = API()
        api.create_index("m")
        api.create_field("m", "mx", {"type": "mutex"})
        api.query("m", "Set(5, mx=1)Set(5, mx=2)")  # mutex replaces
        assert mutex_check(api.holder, "m") == {}


class TestOpsReviewRegressions:
    def test_system_table_rejects_where(self):
        api = API()
        from pilosa_tpu.sql.lexer import SQLError
        with pytest.raises(SQLError):
            api.sql("select query from fb_exec_requests where status = 'x'")

    def test_pending_exclusive_activates_on_expiry(self):
        tm = TransactionManager()
        tm.start("a", timeout_s=0.05)
        tex = tm.start("x", exclusive=True)
        assert not tex.active
        import time
        time.sleep(0.06)
        assert tm.get("x").active  # blocker expired -> activated
        tm.finish("x")

    def test_pending_exclusive_expires(self):
        tm = TransactionManager()
        tm.start("a", timeout_s=100)
        tm.start("x", exclusive=True, timeout_s=0.05)
        import time
        time.sleep(0.06)
        tm.finish("a")
        with pytest.raises(TransactionError):
            tm.get("x")  # expired while pending, not deadlocked
        tm.start("b")  # manager usable again

    def test_ttl_removal_survives_restart(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("t")
        api.create_field("t", "ev", {"type": "time", "timeQuantum": "YMD",
                                     "ttl": 86400})
        api.query("t", "Set(1, ev=5, 2020-01-02T00:00)")
        api.save()  # checkpoint writes the 2020 view's npz files
        removed = remove_expired_views(api.holder,
                                       now=dt.datetime(2099, 1, 1))
        assert removed
        del api
        api2 = API(str(tmp_path))
        field = api2.holder.index("t").field("ev")
        assert not any(v.startswith("standard_2020") for v in field.views)

    def test_metrics_summary_accessor(self):
        from pilosa_tpu.obs.metrics import MetricsRegistry
        r = MetricsRegistry()
        r.observe("x_seconds", 0.5)
        r.observe("x_seconds", 1.5)
        assert r.summary("x_seconds") == (2, 2.0)
