"""Bitwise semi-join SQL plane (pilosa_tpu/sql/joins.py).

Every test's ground truth is the hash-join fallback: the semi plane
must be bit-identical to it (PILOSA_TPU_SEMIJOIN=0 forces the
fallback), and the join metrics tell us which path actually ran — a
test that silently fell back would prove nothing.
"""

import os

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs import tenants as obs_tenants
from pilosa_tpu.obs import tracing as T
from pilosa_tpu.sql import SQLEngine


def _mk(api):
    eng = SQLEngine(api)
    stmts = [
        "create table fact (_id id, fk id, kk string, v int min 0 max "
        "1000, w int min 0 max 1000)",
        "create table dim (_id id, color string, size int min 0 max 100)",
        "create table kdim (_id string, region string)",
        "insert into dim values (1, 'red', 10), (2, 'blue', 20), "
        "(3, 'red', 30), (4, 'green', 40)",
        "insert into kdim values ('a', 'east'), ('b', 'west')",
        "insert into fact values " + ", ".join(
            f"({i}, {i % 4 + 1}, '{'ab'[i % 2]}', {i * 3 % 50}, {i % 7})"
            for i in range(40)),
    ]
    for s in stmts:
        eng.query(s)
    return eng


@pytest.fixture()
def eng():
    return _mk(API())


def _joins_ran():
    return M.REGISTRY.snapshot()["counters"].get(
        "sql_join_queries_total", 0)


def _both(eng, sql):
    """(semi rows, hash rows, semi-path actually taken?)"""
    n0 = _joins_ran()
    semi = eng.query(sql).data
    took = _joins_ran() > n0
    os.environ["PILOSA_TPU_SEMIJOIN"] = "0"
    try:
        hashed = eng.query(sql).data
    finally:
        del os.environ["PILOSA_TPU_SEMIJOIN"]
    return semi, hashed, took


JOIN_SQLS = [
    # case 1: pure semi-join — no dim column outside ON
    "select sum(v) from fact f join dim d on f.fk = d._id "
    "where d.color = 'red'",
    "select count(*) from fact f join dim d on f.fk = d._id "
    "where d.color = 'red' and f.v > 10",
    "select sum(f.v * f.w) from fact f join dim d on f.fk = d._id "
    "where d.size between 10 and 25",
    # reversed ON direction
    "select count(*) from fact f join dim d on d._id = f.fk "
    "where d.color != 'blue'",
    # case 2: dim attrs in projection / grouping / ordering
    "select d.color, sum(f.v) as s from fact f join dim d "
    "on f.fk = d._id group by d.color order by s desc",
    "select f._id, d.color, d.size from fact f join dim d "
    "on f.fk = d._id where d.color = 'blue' order by f._id limit 5",
    # keyed dim via keyed fk
    "select r.region, count(*) from fact f join kdim r "
    "on f.kk = r._id group by r.region order by r.region",
    # multi-dim conjunction
    "select count(*) from fact f join dim d on f.fk = d._id "
    "join kdim r on f.kk = r._id "
    "where d.color = 'red' and r.region = 'east'",
]


class TestBitIdentity:
    @pytest.mark.parametrize("sql", JOIN_SQLS)
    def test_semi_matches_hash(self, eng, sql):
        semi, hashed, took = _both(eng, sql)
        assert took, f"semi plane did not engage for: {sql}"
        assert semi == hashed

    def test_left_join_falls_back(self, eng):
        n0 = _joins_ran()
        f0 = M.REGISTRY.snapshot()["counters"].get(
            "sql_join_fallback_total", 0)
        eng.query("select count(*) from fact f left join dim d "
                  "on f.fk = d._id where d.color = 'red'")
        assert _joins_ran() == n0
        assert M.REGISTRY.snapshot()["counters"].get(
            "sql_join_fallback_total", 0) > f0

    def test_unlowerable_dim_pred_falls_back_not_errors(self, eng):
        # v % 2 has no bitmap form on the dim side
        sql = ("select count(*) from fact f join dim d on f.fk = d._id "
               "where d.size % 2 = 0")
        semi, hashed, took = _both(eng, sql)
        assert not took and semi == hashed

    def test_kill_switch(self, eng):
        os.environ["PILOSA_TPU_SEMIJOIN"] = "0"
        try:
            n0 = _joins_ran()
            eng.query(JOIN_SQLS[0])
            assert _joins_ran() == n0
        finally:
            del os.environ["PILOSA_TPU_SEMIJOIN"]

    def test_no_join_no_cost(self, eng):
        c0 = M.REGISTRY.snapshot()["counters"]
        eng.query("select sum(v) from fact where v > 10")
        c1 = M.REGISTRY.snapshot()["counters"]
        for k in ("sql_join_queries_total", "sql_join_fallback_total",
                  "sql_join_dim_rows_total",
                  "sql_join_broadcast_bytes_total"):
            assert c0.get(k, 0) == c1.get(k, 0)


class TestCacheInvalidation:
    def test_dim_write_invalidates_join_result(self):
        api = API()
        eng = _mk(api)
        api.enable_cache()
        sql = ("select sum(v) from fact f join dim d on f.fk = d._id "
               "where d.color = 'red'")
        before = eng.query(sql).data
        assert eng.query(sql).data == before  # served (from cache or not)
        # recolor dim row 2 blue->red: the cached answer is now wrong
        eng.query("insert into dim values (2, 'red', 20)")
        after = eng.query(sql).data
        os.environ["PILOSA_TPU_SEMIJOIN"] = "0"
        try:
            api.cache.flush()
            want = eng.query(sql).data
        finally:
            del os.environ["PILOSA_TPU_SEMIJOIN"]
        assert after == want
        assert after != before

    def test_join_key_covers_all_tables(self):
        api = API()
        eng = _mk(api)
        from pilosa_tpu.sql.parser import parse_statement
        sql = ("select sum(v) from fact f join dim d on f.fk = d._id "
               "where d.color = 'red'")
        stmt = parse_statement(sql)
        key = eng._select_cache_key(stmt, sql)
        assert key is not None
        tables = [t[0] for t in key[2]]
        assert tables == ["fact", "dim"]


class TestObservability:
    def test_span_stages(self, eng):
        prev = T.get_tracer()
        tracer = T.set_tracer(T.Tracer(enabled=True, sample_rate=1.0,
                                       store=T.TraceStore(8)))
        try:
            span = tracer.start_trace("q")
            with T.span_scope(span):
                eng.query(JOIN_SQLS[4])
            span.finish()
        finally:
            T.set_tracer(prev)

        names = set()

        def walk(s):
            names.add(s.name)
            for c in s.children:
                if not isinstance(c, dict):
                    walk(c)
        walk(span)
        assert "sql.join.dim_scan" in names
        assert "sql.join.broadcast" in names

    def test_tenant_charged_for_dim_legs(self):
        api = API()
        eng = _mk(api)
        api.enable_tenants()
        with obs_tenants.tenant_scope("acme"):
            eng.query("select sum(v) from fact where v > 10")
        base = api.tenants.stats_json()["tenants"]["acme"]["queries"]
        with obs_tenants.tenant_scope("acme"):
            eng.query(JOIN_SQLS[0])
        st = api.tenants.stats_json()["tenants"]["acme"]
        # the dim-index leg is charged on top of whatever the plain
        # query path attributes (query counting happens at the HTTP
        # layer, so base is 0 here — the delta IS the dim leg)
        assert st["queries"] >= base + 1

    def test_dim_rows_and_broadcast_bytes_counted(self, eng):
        c0 = M.REGISTRY.snapshot()["counters"]
        eng.query(JOIN_SQLS[0])
        c1 = M.REGISTRY.snapshot()["counters"]
        assert c1.get("sql_join_dim_rows_total", 0) > \
            c0.get("sql_join_dim_rows_total", 0)
        assert c1.get("sql_join_broadcast_bytes_total", 0) > \
            c0.get("sql_join_broadcast_bytes_total", 0)
