"""SQL conformance suite — table-driven port of the reference's defs
(reference: sql3/test/defs/defs*.go; SURVEY §4.6 calls these executable
specs and says to port the tables). Areas covered: unkeyed/keyed selects,
filter predicates, BETWEEN/IN/LIKE/IS NULL, binops/unops, bool fields,
aggregates, GROUP BY/HAVING, ORDER BY/TOP/LIMIT/OFFSET, DISTINCT, NULL
three-valued logic, JOINs (defs_join.go), DELETE, REPLACE, and a
multi-shard table. Every read-only case runs against BOTH a single-node
API and a non-coordinator node of a 3-node HTTP cluster (the reference
runs defs against an in-process cluster, sql3/sql_test.go) —
the VERDICT r3 #3 done-criterion.
"""

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import LocalCluster

SETUP = [
    # defs_unkeyed.go model
    "create table unkeyed (_id id, an_int int min 0 max 100, "
    "an_id_set idset, an_id id, a_string string, a_string_set stringset, "
    "a_dec decimal(2))",
    "insert into unkeyed values "
    "(1, 11, [11,12,13], 101, 'str1', ['a1','b1','c1'], 123.45),"
    "(2, 22, [21,22,23], 201, 'str2', ['a2','b2','c2'], 234.56),"
    "(3, 33, [31,32,33], 301, 'str3', ['a3','b3','c3'], 345.67),"
    "(4, 44, [41,42,43], 401, 'str4', ['a4','b4','c4'], 456.78)",
    # defs_keyed.go model
    "create table keyed (_id string, v int, tag stringset)",
    "insert into keyed values ('one', 1, ['red']), "
    "('two', 2, ['red','blue']), ('three', 3, ['blue'])",
    # defs_bool.go model
    "create table bools (_id id, b bool)",
    "insert into bools values (1, true), (2, false), (3, true)",
    # defs_groupby.go / defs_aggregate.go model
    "create table agg (_id id, seg id, n int, d decimal(2))",
    "insert into agg values (1, 10, 5, 1.50), (2, 10, 7, 2.25), "
    "(3, 20, 1, 0.75), (4, 20, 3, 1.00), (5, 30, 9, 4.10)",
    # defs_null.go model
    "create table nulls (_id id, a int, s string)",
    "insert into nulls (_id, a, s) values (1, 10, 'x'), (2, null, 'y'), "
    "(3, 20, null)",
    # defs_join.go tables (same data as the reference)
    "create table users (_id id, name string, age int)",
    "insert into users values (0,'a',21),(1,'b',18),(2,'c',28),"
    "(3,'d',34),(4,'e',36)",
    "create table orders (_id id, userid int, price decimal(2))",
    "insert into orders values (0,1,9.99),(1,0,3.99),(2,2,14.99),"
    "(3,3,5.99),(4,1,12.99),(5,2,1.99)",
    # multi-shard table (cluster distribution)
    "create table big (_id id, seg id, n int)",
    "insert into big values (5, 1, 2), (1048581, 1, 3), "
    "(2097157, 2, 4), (10, 2, 1)",
    # defs_cast.go model (cast_int / cast_string source tables)
    "create table casts (_id id, i1 int, d1 decimal(2), s1 string, "
    "b1 bool)",
    "insert into casts values (1, 10, 12.34, '20', true), "
    "(2, -5, 0.50, 'abc', false)",
    # defs_date_functions.go model (dttable)
    "create table dts (_id id, t timestamp, t2 timestamp)",
    "insert into dts values "
    "(1, '2023-01-15T10:30:45Z', '2023-03-20T08:00:00Z'), "
    "(2, '2024-02-29T23:59:59Z', '2024-03-01T00:00:01Z')",
    # defs_minmaxnegative.go model
    "create table neg (_id id, n int, d decimal(2))",
    "insert into neg values (1, -11, -11.50), (2, -22, -0.25), "
    "(3, 33, 3.75), (4, 0, 0.00)",
    # defs_subquery.go model (subquerytable)
    "create table subq (_id id, an_int int, a_string string)",
    "insert into subq values (1, 10, 'str1'), (2, 20, 'str1'), "
    "(3, 30, 'str2'), (4, 40, 'str3')",
    # defs_set_functions.go model (selectwithsetliterals)
    "create table setfn (_id id, event stringset, ievent idset)",
    "insert into setfn values (1, ['POST','GET'], [100, 101]), "
    "(2, ['GET'], [100]), (3, ['DELETE'], [102])",
    # defs_timequantum.go model (time_quantum_insert)
    "create table tqi (_id id, i1 int, ss1 stringsetq timequantum 'YMD', "
    "ids1 idsetq timequantum 'YMD')",
    "insert into tqi (_id, i1, ss1, ids1) values "
    "(1, 1, {'2022-01-02T00:00:00Z', ['a']}, {'2022-01-02T00:00:00Z', [1]})",
    "insert into tqi (_id, i1, ss1, ids1) values "
    "(2, 2, {'2022-03-05T00:00:00Z', ['b']}, [9])",
]

# (name, sql, expected rows, ordered)
CASES = [
    # -- selects & filter predicates (defs_unkeyed/defs_filterpredicates) --
    ("select-cols", "select _id, an_int from unkeyed",
     [[1, 11], [2, 22], [3, 33], [4, 44]], False),
    ("top", "select top(2) _id from unkeyed", [[1], [2]], False),
    ("where-int-eq", "select _id from unkeyed where an_int = 22",
     [[2]], False),
    ("where-string-eq", "select _id from unkeyed where a_string = 'str2'",
     [[2]], False),
    ("where-id-eq", "select _id from unkeyed where an_id = 201",
     [[2]], False),
    ("where-idset", "select _id from unkeyed where setcontains(an_id_set, 21)",
     [[2]], False),
    ("where-stringset",
     "select _id from unkeyed where setcontains(a_string_set, 'a2')",
     [[2]], False),
    ("where-ne", "select _id from unkeyed where an_int != 22",
     [[1], [3], [4]], False),
    ("where-lt", "select _id from unkeyed where an_int < 33",
     [[1], [2]], False),
    ("where-le", "select _id from unkeyed where an_int <= 33",
     [[1], [2], [3]], False),
    ("where-gt", "select _id from unkeyed where an_int > 22",
     [[3], [4]], False),
    ("where-ge", "select _id from unkeyed where an_int >= 22",
     [[2], [3], [4]], False),
    ("where-and",
     "select _id from unkeyed where an_int > 11 and an_int < 44",
     [[2], [3]], False),
    ("where-or",
     "select _id from unkeyed where an_int = 11 or an_int = 44",
     [[1], [4]], False),
    ("where-not", "select _id from unkeyed where not an_int = 22",
     [[1], [3], [4]], False),
    ("where-id-filter", "select an_int from unkeyed where _id = 3",
     [[33]], False),
    ("where-id-in", "select _id from unkeyed where _id in (1, 4)",
     [[1], [4]], False),
    # -- BETWEEN / IN (defs_between.go, defs_in.go) ------------------------
    ("between", "select _id from unkeyed where an_int between 22 and 33",
     [[2], [3]], False),
    ("not-between",
     "select _id from unkeyed where an_int not between 22 and 33",
     [[1], [4]], False),
    ("in", "select _id from unkeyed where an_int in (11, 33)",
     [[1], [3]], False),
    ("not-in", "select _id from unkeyed where an_int not in (11, 33)",
     [[2], [4]], False),
    # -- LIKE (defs_like.go) -----------------------------------------------
    ("like-prefix", "select _id from unkeyed where a_string like 'str%'",
     [[1], [2], [3], [4]], False),
    ("like-suffix", "select _id from unkeyed where a_string like '%2'",
     [[2]], False),
    ("not-like", "select _id from unkeyed where a_string not like '%2'",
     [[1], [3], [4]], False),
    # -- binops / unops (defs_binops.go, defs_unops.go) --------------------
    ("proj-arith", "select _id, an_int + 1 from unkeyed where _id = 1",
     [[1, 12]], False),
    ("proj-mul", "select an_int * 2 from unkeyed where _id = 2",
     [[44]], False),
    ("binop-const", "select 2 + 3 * 4", [[14]], False),
    ("binop-intdiv", "select 7 / 2", [[3]], False),
    ("binop-mod", "select 10 % 3", [[1]], False),
    ("unop-neg", "select -5", [[-5]], False),
    # -- bool fields (defs_bool.go) ----------------------------------------
    ("bool-true", "select _id from bools where b = true",
     [[1], [3]], False),
    ("bool-false", "select _id from bools where b = false", [[2]], False),
    ("bool-bare", "select _id from bools where b", [[1], [3]], False),
    ("bool-not", "select _id from bools where not b", [[2]], False),
    # -- aggregates (defs_aggregate.go) ------------------------------------
    ("count-star", "select count(*) from unkeyed", [[4]], False),
    ("count-col", "select count(an_int) from unkeyed", [[4]], False),
    ("sum", "select sum(an_int) from unkeyed", [[110]], False),
    ("avg", "select avg(an_int) from unkeyed", [[27.5]], False),
    ("min", "select min(an_int) from unkeyed", [[11]], False),
    ("max", "select max(an_int) from unkeyed", [[44]], False),
    ("sum-filtered", "select sum(an_int) from unkeyed where an_int > 20",
     [[99]], False),
    ("count-filtered", "select count(*) from unkeyed where an_int >= 22",
     [[3]], False),
    ("count-distinct", "select count(distinct seg) from agg", [[3]], False),
    ("count-distinct-n", "select count(distinct n) from agg", [[5]], False),
    ("sum-distinct", "select sum(distinct n) from agg", [[25]], False),
    # -- GROUP BY / HAVING (defs_groupby.go, defs_having.go) ---------------
    ("groupby-count", "select seg, count(*) from agg group by seg",
     [[10, 2], [20, 2], [30, 1]], False),
    ("groupby-sum", "select seg, sum(n) from agg group by seg",
     [[10, 12], [20, 4], [30, 9]], False),
    ("groupby-where",
     "select seg, count(*) from agg where n > 2 group by seg",
     [[10, 2], [20, 1], [30, 1]], False),
    ("groupby-having",
     "select seg, count(*) from agg group by seg having count(*) > 1",
     [[10, 2], [20, 2]], False),
    ("groupby-min", "select seg, min(n) from agg group by seg",
     [[10, 5], [20, 1], [30, 9]], False),
    ("groupby-max", "select seg, max(n) from agg group by seg",
     [[10, 7], [20, 3], [30, 9]], False),
    ("groupby-avg", "select seg, avg(n) from agg group by seg",
     [[10, 6.0], [20, 2.0], [30, 9.0]], False),
    ("groupby-order-agg",
     "select seg, sum(n) from agg group by seg order by sum(n) desc",
     [[10, 12], [30, 9], [20, 4]], True),
    # -- ORDER BY / LIMIT / OFFSET (defs_orderby.go, defs_top.go) ----------
    ("orderby-desc", "select _id from unkeyed order by an_int desc",
     [[4], [3], [2], [1]], True),
    ("orderby-asc", "select _id, an_int from unkeyed order by an_int",
     [[1, 11], [2, 22], [3, 33], [4, 44]], True),
    ("limit-offset",
     "select _id from unkeyed order by _id limit 2 offset 1",
     [[2], [3]], True),
    # -- DISTINCT (defs_distinct.go) ---------------------------------------
    ("distinct-seg", "select distinct seg from agg",
     [[10], [20], [30]], False),
    # -- NULL three-valued logic (defs_null.go) ----------------------------
    ("null-is", "select _id from nulls where a is null", [[2]], False),
    ("null-isnot", "select _id from nulls where a is not null",
     [[1], [3]], False),
    ("null-s-is", "select _id from nulls where s is null", [[3]], False),
    ("null-count", "select count(a) from nulls", [[2]], False),
    ("null-sum", "select sum(a) from nulls", [[30]], False),
    ("null-cmp-excludes", "select _id from nulls where a > 5",
     [[1], [3]], False),
    ("null-ne-excludes", "select _id from nulls where a != 10",
     [[3]], False),
    ("null-proj", "select a + 1 from nulls where _id = 2", [[None]], False),
    # -- keyed tables (defs_keyed.go) --------------------------------------
    ("keyed-select", "select _id, v from keyed order by v",
     [["one", 1], ["two", 2], ["three", 3]], True),
    ("keyed-where-id", "select v from keyed where _id = 'two'",
     [[2]], False),
    ("keyed-set", "select _id from keyed where setcontains(tag, 'red')",
     [["one"], ["two"]], False),
    ("keyed-sum", "select sum(v) from keyed", [[6]], False),
    # -- JOINs (defs_join.go — same data and expected values) --------------
    ("join-groupby",
     "select u._id, sum(orders.price) from orders o inner join users u "
     "on o.userid = u._id group by u._id",
     [[0, 3.99], [1, 22.98], [2, 16.98], [3, 5.99]], False),
    ("join-sum-filter",
     "select sum(price) from orders o inner join users u "
     "on o.userid = u._id where u.age > 20",
     [[26.96]], False),
    ("join-sum-double-filter",
     "select sum(price) from orders o inner join users u "
     "on o.userid = u._id where u.age > 20 and o.price < 10.00",
     [[11.97]], False),
    ("join-count-distinct",
     "SELECT COUNT(DISTINCT u.name) FROM orders o JOIN users u "
     "ON o.userid = u._id WHERE o.price > 9",
     [[2]], False),
    ("join-left",
     "select u.name, o.price from users u left join orders o "
     "on o.userid = u._id order by u.name, o.price",
     [["a", 3.99], ["b", 9.99], ["b", 12.99], ["c", 1.99], ["c", 14.99],
      ["d", 5.99], ["e", None]], True),
    ("join-count", "select count(*) from orders o join users u "
     "on o.userid = u._id", [[6]], False),
    # -- CAST (defs_cast.go; literal + column forms) -----------------------
    ("cast-int-int", "select cast(1 as int)", [[1]], False),
    ("cast-int-bool", "select cast(1 as bool)", [[True]], False),
    ("cast-zero-bool", "select cast(0 as bool)", [[False]], False),
    ("cast-int-string", "select cast(1 as string)", [["1"]], False),
    ("cast-int-id", "select cast(1 as id)", [[1]], False),
    ("cast-int-timestamp", "select cast(1000 as timestamp)",
     [["1970-01-01T00:16:40Z"]], False),
    ("cast-string-int", "select cast('20' as int)", [[20]], False),
    ("cast-bool-string", "select cast(true as string)", [["true"]], False),
    ("cast-col-int", "select _id, cast(i1 as int) from casts",
     [[1, 10], [2, -5]], False),
    ("cast-col-bool", "select _id, cast(i1 as bool) from casts",
     [[1, True], [2, True]], False),
    ("cast-col-string", "select _id, cast(i1 as string) from casts",
     [[1, "10"], [2, "-5"]], False),
    ("cast-col-decimal", "select _id, cast(d1 as decimal(1)) from casts",
     [[1, 12.3], [2, 0.5]], False),
    ("cast-string-col-int",
     "select cast(s1 as int) from casts where _id = 1", [[20]], False),
    # -- string functions (defs_string_functions.go; expected values are
    #    the reference's) -------------------------------------------------
    ("str-reverse-empty", "select reverse('')", [[""]], False),
    ("str-reverse", "select reverse('this')", [["siht"]], False),
    ("str-reverse-reverse", "select reverse(reverse('this'))",
     [["this"]], False),
    ("str-reverse-null", "select reverse(null)", [[None]], False),
    ("str-substring", "select substring('testing', 1, 3)", [["est"]], False),
    ("str-substring-tail", "select substring('testing', 4)",
     [["ing"]], False),
    ("str-substring-rev", "select substring(reverse('testing'), 3)",
     [["tset"]], False),
    ("str-substring-null", "select substring(null, 1, 3)", [[None]], False),
    ("str-replaceall",
     "select replaceall('hello database','data','feature')",
     [["hello featurebase"]], False),
    ("str-replaceall-null",
     "select replaceall('hello database',null,'feature')", [[None]], False),
    ("str-replaceall-nested",
     "select replaceall(reverse('gnitset'),substring('testing',4),"
     "upper('ed'))", [["testED"]], False),
    ("str-charindex", "select charindex('is','this is great')", [[2]], False),
    ("str-charindex-pos", "select charindex('is','this is great',3)",
     [[5]], False),
    ("str-charindex-missing", "select charindex('abc','this is great',3)",
     [[-1]], False),
    ("str-charindex-null", "select charindex(null,'this is great',3)",
     [[None]], False),
    ("str-trim", "select trim('  this  ')", [["this"]], False),
    ("str-rtrim", "select rtrim('  this  ')", [["  this"]], False),
    ("str-ltrim", "select ltrim('  this  ')", [["this  "]], False),
    ("str-space", "select space(5)", [["     "]], False),
    ("str-space-zero", "select space(0)", [[""]], False),
    ("str-space-null", "select space(null)", [[None]], False),
    ("str-str", "select str(12345)", [["     12345"]], False),
    ("str-str-len", "select str(12345, 5)", [["12345"]], False),
    ("str-str-overflow", "select str(12345, 5, 5)", [["*****"]], False),
    ("str-str-round", "select str(12345.678)", [["     12346"]], False),
    ("str-ascii", "select ascii('R')", [[82]], False),
    ("str-char", "select char(82)", [["R"]], False),
    ("str-format", "select format('this or %s', 'that')",
     [["this or that"]], False),
    ("str-format-bool", "select format('is this %t?', true)",
     [["is this true?"]], False),
    ("str-format-int", "select format('%d > %d', 11, 9)",
     [["11 > 9"]], False),
    ("str-format-noarg", "select format('noArg')", [["noArg"]], False),
    ("str-upper-col", "select _id, upper(s1) from casts",
     [[1, "20"], [2, "ABC"]], False),
    # -- date functions (defs_date_functions.go; YY/YD/M/D/W/WK/HH/MI/S
    #    interval names) --------------------------------------------------
    ("dt-part-yy", "select datetimepart('yy', '2023-06-01T11:22:33Z')",
     [[2023]], False),
    ("dt-part-m", "select datetimepart('m', '2023-06-01T11:22:33Z')",
     [[6]], False),
    ("dt-part-d", "select datetimepart('d', '2023-06-01T11:22:33Z')",
     [[1]], False),
    ("dt-part-hh", "select datetimepart('hh', '2023-06-01T11:22:33Z')",
     [[11]], False),
    ("dt-part-mi", "select datetimepart('mi', '2023-06-01T11:22:33Z')",
     [[22]], False),
    ("dt-part-s", "select datetimepart('s', '2023-06-01T11:22:33Z')",
     [[33]], False),
    ("dt-part-yd", "select datetimepart('yd', '2023-02-01T00:00:00Z')",
     [[32]], False),
    ("dt-part-col", "select _id, datetimepart('yy', t) from dts",
     [[1, 2023], [2, 2024]], False),
    ("dt-add-yy", "select datetimeadd('yy', 2, '2023-11-15T01:02:03Z')",
     [["2025-11-15T01:02:03Z"]], False),
    ("dt-add-m-wrap", "select datetimeadd('m', 2, '2023-11-15T00:00:00Z')",
     [["2024-01-15T00:00:00Z"]], False),
    ("dt-add-d", "select datetimeadd('d', 10, '2023-12-25T12:00:00Z')",
     [["2024-01-04T12:00:00Z"]], False),
    ("dt-add-s-null", "select datetimeadd('s', null, t) from dts where "
     "_id = 1", [[None]], False),
    ("dt-diff-d", "select datetimediff('d', '2023-01-01T00:00:00Z', "
     "'2023-03-01T00:00:00Z')", [[59]], False),
    ("dt-diff-col", "select _id, datetimediff('d', t, t2) from dts",
     [[1, 63], [2, 0]], False),
    ("dt-name-month", "select datetimename('m', '2023-06-01T00:00:00Z')",
     [["June"]], False),
    ("dt-totimestamp-ms", "select totimestamp(1000, 'ms')",
     [["1970-01-01T00:00:01Z"]], False),
    ("dt-totimestamp-s", "select totimestamp(1000)",
     [["1970-01-01T00:16:40Z"]], False),
    # -- percentile (defs_aggregate.go percentile cases) -------------------
    ("pct-0", "select percentile(n, 0) from agg", [[1]], False),
    ("pct-50", "select percentile(n, 50) from agg", [[5]], False),
    ("pct-100", "select percentile(n, 100) from agg", [[9]], False),
    ("pct-where", "select percentile(n, 50) from agg where seg = 10",
     [[5]], False),
    # -- min/max over negatives (defs_minmaxnegative.go) -------------------
    ("neg-min", "select min(n) from neg", [[-22]], False),
    ("neg-max", "select max(n) from neg", [[33]], False),
    ("neg-min-dec", "select min(d) from neg", [[-11.5]], False),
    ("neg-max-dec", "select max(d) from neg", [[3.75]], False),
    ("neg-sum", "select sum(n) from neg", [[0]], False),
    ("neg-where-lt", "select _id from neg where n < 0",
     [[1], [2]], False),
    ("neg-between", "select _id from neg where n between -25 and -5",
     [[1], [2]], False),
    # -- more null semantics (defs_null.go) --------------------------------
    ("null-arith", "select 1 + null", [[None]], False),
    ("null-cast", "select cast(null as int)", [[None]], False),
    ("null-eq-null", "select count(*) from nulls where a = null",
     [[0]], False),
    ("null-isnull-notnull",
     "select _id from nulls where a is not null and s is not null",
     [[1]], False),
    # -- FROM-subqueries / derived tables (defs_subquery.go) ---------------
    ("subq-sum-of-counts",
     "select sum(mycount) as thecount from (select count(a_string) as "
     "mycount, a_string from subq group by a_string)", [[4]], False),
    ("subq-sum-of-distinct-counts",
     "select sum(mycount) as thecount from (select count(distinct "
     "a_string) as mycount, a_string from subq group by a_string)",
     [[3]], False),
    ("subq-outer-where",
     "select a_string, total from (select a_string, sum(an_int) as "
     "total from subq group by a_string) t where total > 25",
     [["str1", 30], ["str2", 30], ["str3", 40]], False),
    ("subq-nested",
     "select max(total) from (select sum(an_int) as total from "
     "(select a_string, an_int from subq) x group by a_string) y",
     [[40]], False),
    # -- set functions projected in the select list (defs_set_functions) ---
    ("setfn-contains-proj",
     "select _id, setcontains(event, 'POST') from setfn",
     [[1, True], [2, False], [3, False]], False),
    ("setfn-containsall-proj",
     "select _id, setcontainsall(event, ['POST','GET']) from setfn",
     [[1, True], [2, False], [3, False]], False),
    ("setfn-containsany-proj",
     "select _id, setcontainsany(event, ['POST','DELETE']) from setfn",
     [[1, True], [2, False], [3, True]], False),
    ("setfn-id-contains",
     "select _id from setfn where setcontains(ievent, 101)",
     [[1]], False),
    ("setfn-id-any",
     "select _id from setfn where setcontainsany(ievent, [101, 102])",
     [[1], [3]], False),
    ("setfn-literal-target",
     "select _id, setcontains(['POST'], 'POST') from setfn where _id = 1",
     [[1, True]], False),
    # -- time quantum (defs_timequantum.go: rangeq + tuple inserts) --------
    ("tq-rangeq-window",
     "select _id from tqi where rangeq(ss1, '2022-01-01T00:00:00Z', "
     "'2022-02-01T00:00:00Z')", [[1]], False),
    ("tq-rangeq-open-start",
     "select _id from tqi where rangeq(ss1, null, "
     "'2022-02-01T00:00:00Z')", [[1]], False),
    ("tq-rangeq-open-end",
     "select _id from tqi where rangeq(ss1, '2022-02-01T00:00:00Z', "
     "null)", [[2]], False),
    ("tq-rangeq-all",
     "select _id from tqi where rangeq(ss1, null, null)",
     [[1], [2]], False),
    ("tq-rangeq-idset",
     "select _id from tqi where rangeq(ids1, '2022-01-01T00:00:00Z', "
     "'2022-02-01T00:00:00Z')", [[1]], False),
    ("tq-plain-set-insert-visible",
     "select _id from tqi where setcontains(ids1, 9)", [[2]], False),
    # -- multi-shard (cluster distribution) --------------------------------
    ("big-count", "select count(*) from big", [[4]], False),
    ("big-sum", "select sum(n) from big", [[10]], False),
    ("big-groupby", "select seg, sum(n) from big group by seg",
     [[1, 5], [2, 5]], False),
    ("big-where", "select _id from big where n >= 3",
     [[1048581], [2097157]], False),
]


def _norm(v):
    if isinstance(v, list):
        return tuple(sorted(map(str, v)))
    if isinstance(v, float):
        return round(v, 6)
    return v


def _rows(res):
    return [[_norm(v) for v in row] for row in res.data]


def _check(backend, sql, expected, ordered):
    got = _rows(backend.sql(sql))
    want = [[_norm(v) for v in row] for row in expected]
    if not ordered:
        got = sorted(got, key=repr)
        want = sorted(want, key=repr)
    assert got == want, f"{sql}\n got: {got}\nwant: {want}"


@pytest.fixture(scope="module")
def single():
    api = API()
    for stmt in SETUP:
        api.sql(stmt)
    return api


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(3)
    for stmt in SETUP:
        c.coordinator.sql(stmt)
    yield c
    c.close()


@pytest.mark.parametrize("name,sql,expected,ordered",
                         CASES, ids=[c[0] for c in CASES])
def test_defs_single_node(single, name, sql, expected, ordered):
    _check(single, sql, expected, ordered)


@pytest.mark.parametrize("name,sql,expected,ordered",
                         CASES, ids=[c[0] for c in CASES])
def test_defs_cluster_3node(cluster, name, sql, expected, ordered):
    # a NON-coordinator node serves every case: schema arrived by
    # broadcast, data by shard routing (reference: sql3 defs run against
    # test.MustRunCluster)
    _check(cluster[1], sql, expected, ordered)


def test_star_schema(single):
    res = single.sql("select * from unkeyed")
    assert sorted(n for n, _ in res.schema) == [
        "_id", "a_dec", "a_string", "a_string_set", "an_id", "an_id_set",
        "an_int"]
    assert len(res.data) == 4


class TestDefsDML:
    """DELETE / REPLACE semantics (defs_delete.go, defs_keyed_insert.go)
    — mutating, so each test builds its own table."""

    def test_delete_where(self):
        api = API()
        api.sql("create table del1 (_id id, v int)")
        api.sql("insert into del1 values (1,1),(2,2),(3,3),(4,4)")
        api.sql("delete from del1 where v > 2")
        assert api.sql("select count(*) from del1").data == [[2]]
        api.sql("delete from del1")
        assert api.sql("select count(*) from del1").data == [[0]]

    def test_replace_resets_sets(self):
        api = API()
        api.sql("create table ups (_id id, tag idset)")
        api.sql("insert into ups values (1, [1, 2])")
        api.sql("replace into ups values (1, [3])")
        assert _rows(api.sql("select tag from ups")) == [[("3",)]]

    def test_insert_merges_sets(self):
        api = API()
        api.sql("create table ups2 (_id id, tag idset)")
        api.sql("insert into ups2 values (1, [1, 2])")
        api.sql("insert into ups2 values (1, [3])")
        assert _rows(api.sql("select tag from ups2")) == [[("1", "2", "3")]]

    def test_cluster_delete(self):
        c = LocalCluster(3)
        try:
            co = c.coordinator
            co.sql("create table cdel (_id id, v int)")
            co.sql("insert into cdel values (5,1),(1048581,2),(2097157,3)")
            assert c[1].sql("select count(*) from cdel").data == [[3]]
            co.sql("delete from cdel where v >= 2")
            assert c[2].sql("select count(*) from cdel").data == [[1]]
        finally:
            c.close()


class TestReviewRegressions:
    """Fixes from the round-4 review: residual JOIN conjuncts must have
    their columns projected; single-table queries accept their own
    qualifier."""

    def test_join_unlowerable_where_conjunct(self):
        api = API()
        api.sql("create table o2 (_id id, userid int, price int)")
        api.sql("create table u2 (_id id, age int)")
        api.sql("insert into o2 values (0, 1, 16), (1, 2, 5)")
        api.sql("insert into u2 values (1, 30), (2, 40)")
        # `price + 0 > 15` has no PQL form -> host residual above the
        # join; its column must still be scanned
        r = api.sql("select o2._id from o2 inner join u2 "
                    "on o2.userid = u2._id where o2.price + 0 > 15")
        assert r.data == [[0]], r.data

    def test_single_table_alias_qualifier(self):
        api = API()
        api.sql("create table sq (_id id, price int)")
        api.sql("insert into sq values (1, 5), (2, 9)")
        assert api.sql("select o.price from sq o where o.price > 6"
                       ).data == [[9]]
        assert api.sql("select sq.price from sq").data == [[5], [9]]
        with pytest.raises(Exception):
            api.sql("select zz.price from sq o")

    def test_insert_empty_set_literal_record_exists(self):
        """A record whose only non-id value is an empty set literal must
        still exist (review fix: the _exists bit was skipped)."""
        api = API()
        api.sql("create table es (_id id, tag idset)")
        api.sql("insert into es values (1, [])")
        assert api.sql("select count(*) from es").data == [[1]]
        assert api.sql("select _id from es").data == [[1]]


class TestViews:
    """CREATE VIEW / DROP VIEW (reference: sql3 CREATE VIEW,
    defs_views.go behaviors)."""

    @pytest.fixture()
    def api(self):
        api = API()
        api.sql("create table base (_id id, seg id, n int)")
        api.sql("insert into base values (1, 10, 5), (2, 10, 7), "
                "(3, 20, 1), (4, 20, 3)")
        return api

    def test_view_select(self, api):
        api.sql("create view big as select _id, seg, n from base "
                "where n > 2")
        assert sorted(api.sql("select _id from big").data) == \
            [[1], [2], [4]]
        # outer WHERE + projection over the view
        assert api.sql("select _id from big where seg = 10").data in \
            ([[1], [2]], [[2], [1]])
        assert api.sql("select count(*) from big").data == [[3]]

    def test_view_aggregate_and_order(self, api):
        api.sql("create view v as select seg, n from base")
        out = api.sql("select seg, sum(n) from v group by seg "
                      "order by sum(n) desc")
        assert out.data == [[10, 12], [20, 4]]

    def test_view_of_view_and_cycle_guard(self, api):
        api.sql("create view v1 as select _id, n from base where n > 1")
        api.sql("create view v2 as select _id from v1 where n > 4")
        assert sorted(api.sql("select _id from v2").data) == [[1], [2]]
        # cycle: v3 -> v3 rejected at definition (validation plans it)
        with pytest.raises(Exception):
            api.sql("create view v3 as select _id from v3")

    def test_view_ddl_semantics(self, api):
        api.sql("create view v as select _id from base")
        with pytest.raises(Exception):
            api.sql("create view v as select _id from base")
        api.sql("create view if not exists v as select _id from base")
        api.sql("drop view v")
        with pytest.raises(Exception):
            api.sql("select _id from v")
        api.sql("drop view if exists v")
        with pytest.raises(Exception):
            api.sql("drop view v")

    def test_view_validates_at_definition(self, api):
        with pytest.raises(Exception):
            api.sql("create view bad as select nope from base")
        with pytest.raises(Exception):
            api.sql("create view bad2 as select _id from missing_table")


class TestFunctionEdges:
    """Round-5 review findings: SQL function error surfaces and
    normalization (uncaught ValueErrors must be SQLErrors; month/year
    adds normalize day overflow like Go's time.AddDate)."""

    @pytest.fixture(scope="class")
    def api(self):
        return API()

    def test_datetimeadd_day_overflow_normalizes(self, api):
        assert api.sql(
            "select datetimeadd('m', 1, '2023-01-31T00:00:00Z')"
        ).data == [["2023-03-03T00:00:00Z"]]
        assert api.sql(
            "select datetimeadd('yy', 1, '2024-02-29T00:00:00Z')"
        ).data == [["2025-03-01T00:00:00Z"]]

    def test_cast_errors_are_sql_errors(self, api):
        from pilosa_tpu.sql.lexer import SQLError
        for q in ("select cast('abc' as decimal(2))",
                  "select cast('notadate' as timestamp)",
                  "select cast('abc' as int)",
                  "select format('%d', 'x')"):
            with pytest.raises(SQLError):
                api.sql(q)

    def test_cast_timestamp_normalizes(self, api):
        assert api.sql(
            "select cast('2023-01-15T10:30:45+00:00' as timestamp)"
        ).data == [["2023-01-15T10:30:45Z"]]

    def test_datetimediff_ns_exact(self, api):
        got = api.sql(
            "select datetimediff('ns', '2020-01-01T00:00:00Z', "
            "'2021-01-01T00:00:00.000001Z')").data[0][0]
        assert got == 31622400000001000
