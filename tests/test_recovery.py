"""Crash-consistent recovery plane: segmented WAL + checkpoints,
kill-point crash injection, replica catch-up via log shipping.

The core invariant, asserted from three directions:

* a crash at ANY byte of the write path leaves a snapshot + WAL tail
  that replays to exactly the last flushed commit (kill-point matrix vs
  an uncrashed oracle);
* the same WAL tail applied twice produces identical planes (replay
  idempotence, which is what makes fuzzy checkpoints and catch-up
  overlap safe);
* a lagging replica catches up over /internal/recovery/{snapshot,wal}
  to answer bit-identically, with mid-catch-up writes queued.

``PILOSA_TPU_CRASH_SEED`` (scripts/tier1.sh crash lane) steers the
seeded kill point the same way PILOSA_TPU_FAULT_SEED steers RPC faults.
"""

import os

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.resilience import FaultPlan
from pilosa_tpu.config import Config
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage.recovery import (
    CHECKPOINT_META, CRASH_SITES, CrashPlan, RecoveryManager,
    SimulatedCrash, abandon_holder, attach_crash_plan, crash_workload,
    filter_record, oracle_checksums, read_checkpoint_meta, record_shards,
    run_crash_point, write_checkpoint_meta,
)
from pilosa_tpu.storage.wal import WAL, iter_frames


# -- segmented WAL -----------------------------------------------------------


class TestSegmentedWAL:
    def test_rotation_produces_numbered_segments(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"), segment_bytes=64)
        lsns = [w.append(("set_bit", "f", "", i, i)) for i in range(8)]
        w.flush()
        assert lsns == sorted(lsns) and len(set(lsns)) == 8
        segs = sorted(p.name for p in tmp_path.iterdir()
                      if p.name.startswith("wal.log."))
        assert len(segs) > 1  # 64-byte segments force rotation
        assert segs[0] == "wal.log.00000001"
        assert [r for r in w.records()] == \
            [("set_bit", "f", "", i, i) for i in range(8)]
        w.close()

    def test_lsn_survives_reopen_and_truncate(self, tmp_path):
        p = str(tmp_path / "wal.log")
        w = WAL(p, segment_bytes=64)
        for i in range(5):
            w.append(("set_bit", "f", "", 0, i))
        w.flush()
        top = w.last_lsn
        w.close()
        w2 = WAL(p, segment_bytes=64)
        assert w2.last_lsn == top
        old_seqs = {int(q.name.rsplit(".", 1)[1]) for q in tmp_path.iterdir()}
        w2.truncate()
        assert w2.last_lsn == top  # the counter NEVER resets
        new_seqs = {int(q.name.rsplit(".", 1)[1]) for q in tmp_path.iterdir()}
        assert min(new_seqs) > max(old_seqs)  # fresh segment, later seq
        assert w2.append(("set_bit", "f", "", 0, 9)) == top + 1
        w2.close()

    def test_prune_drops_only_wholly_covered_segments(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"), segment_bytes=64)
        lsns = [w.append(("set_bit", "f", "", 0, i)) for i in range(9)]
        w.flush()
        n_before = len(list(tmp_path.iterdir()))
        assert n_before > 2
        mid = lsns[4]
        w.prune(mid)
        # every record above the checkpoint LSN must still replay
        kept = [lsn for lsn, _rec, _n in w.replay(after_lsn=mid)]
        assert kept == lsns[5:]
        # and pruning everything leaves the (empty) active segment only
        w.prune(w.last_lsn)
        assert w.record_bytes == 0
        assert list(w.records()) == []
        w.close()

    def test_legacy_single_file_adopted_as_segment(self, tmp_path):
        p = str(tmp_path / "wal.log")
        w = WAL(p)
        w.append(("set_bit", "f", "", 1, 2))
        w.flush()
        w.close()
        # simulate a pre-segmentation install: one bare wal.log file
        os.rename(w.path, p)
        for q in tmp_path.iterdir():
            assert q.name == "wal.log"
        w2 = WAL(p)
        assert list(w2.records()) == [("set_bit", "f", "", 1, 2)]
        assert not os.path.exists(p)  # renamed into the segment scheme
        w2.close()

    def test_legacy_ii_framed_log_converted_not_truncated(self, tmp_path):
        """Regression: a TRUE pre-segmentation log uses <II> framing
        (crc over payload alone, no LSN). Renaming it untouched fails
        every new-framing CRC, scans as torn at byte 0, and the first
        repair() silently truncates all its committed records; adoption
        must rewrite it with synthesized LSNs instead."""
        import pickle
        import struct
        import zlib

        recs = [("set_bit", "f", "", r, r + 1) for r in range(5)]
        p = str(tmp_path / "wal.log")
        with open(p, "wb") as f:
            for rec in recs:
                payload = pickle.dumps(rec, protocol=5)
                f.write(struct.pack("<II", zlib.crc32(payload),
                                    len(payload)) + payload)
        w = WAL(p)
        assert not os.path.exists(p)  # converted into the segment scheme
        assert list(w.records()) == recs
        assert [lsn for lsn, _r, _n in w.replay(0)] == [1, 2, 3, 4, 5]
        w.repair()  # a no-op: the converted segment is intact
        assert list(w.records()) == recs
        assert w.append(("set_bit", "f", "", 9, 9)) == 6  # LSNs continue
        w.flush()
        w.close()
        w2 = WAL(p)  # stable across a second open
        assert len(list(w2.records())) == 6
        w2.close()

    def test_legacy_log_torn_tail_keeps_intact_prefix(self, tmp_path):
        import pickle
        import struct
        import zlib

        recs = [("set_bit", "f", "", r, r) for r in range(3)]
        p = str(tmp_path / "wal.log")
        with open(p, "wb") as f:
            for rec in recs:
                payload = pickle.dumps(rec, protocol=5)
                f.write(struct.pack("<II", zlib.crc32(payload),
                                    len(payload)) + payload)
            f.write(b"\x01\x02\x03")  # torn mid-append legacy header
        w = WAL(p)
        assert list(w.records()) == recs
        w.close()


class TestTornTailVsMarker:
    def test_byte_exact_torn_tail_drops_only_last_write(self, tmp_path):
        """Regression for the zero-payload/torn-header conflation: a tear
        at any byte of the final frame must drop that frame only."""
        recs = [("set_bit", "f", "", 0, 1), ("import_bits", "f", [1], [9])]
        p = str(tmp_path / "wal.log")
        w = WAL(p)
        w.append(recs[0])
        w.flush()
        size_first = os.path.getsize(w.path)
        w.append(recs[1])
        w.flush()
        active = w.path
        w.close()
        with open(active, "rb") as f:
            blob = f.read()
        assert size_first < len(blob)
        for cut in range(size_first, len(blob)):  # every torn byte count
            with open(active, "wb") as f:
                f.write(blob[:cut])
            w2 = WAL(p)
            assert list(w2.records()) == recs[:1], f"cut at {cut} bytes"
            w2.close()
        # restoring the full file yields both again
        with open(active, "wb") as f:
            f.write(blob)
        w3 = WAL(p)
        assert list(w3.records()) == recs
        w3.close()

    def test_segment_markers_do_not_stop_replay(self, tmp_path):
        """Each segment opens with a zero-payload marker frame; replay
        must skip them, not treat them as a tear (the old behavior)."""
        w = WAL(str(tmp_path / "wal.log"), segment_bytes=1)  # rotate always
        recs = [("set_bit", "f", "", 0, i) for i in range(4)]
        for r in recs:
            w.append(r)
        w.flush()
        assert len(list(tmp_path.iterdir())) >= 4  # one record per segment
        assert list(w.records()) == recs
        w.close()

    def test_corrupt_interior_byte_stops_at_tear(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"))
        w.append(("set_bit", "f", "", 0, 1))
        w.append(("set_bit", "f", "", 0, 2))
        w.flush()
        active = w.path
        w.close()
        with open(active, "r+b") as f:
            f.seek(20)  # inside the first record's frame (after marker)
            b = f.read(1)
            f.seek(20)
            f.write(bytes([b[0] ^ 0xFF]))
        assert list(WAL(str(tmp_path / "wal.log")).records()) == []

    def test_repair_truncates_to_valid_prefix(self, tmp_path):
        p = str(tmp_path / "wal.log")
        w = WAL(p)
        w.append(("set_bit", "f", "", 0, 1))
        w.flush()
        good = os.path.getsize(w.path)
        active = w.path
        w.close()
        with open(active, "ab") as f:
            f.write(b"\x01\x02\x03")  # torn garbage
        w2 = WAL(p)
        w2.repair()
        assert os.path.getsize(active) == good
        assert list(w2.records()) == [("set_bit", "f", "", 0, 1)]
        w2.close()


class TestTailShipping:
    def test_tail_bytes_round_trips_through_iter_frames(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"), segment_bytes=96)
        recs = [("import_bits", "f", [i], [i * 3]) for i in range(6)]
        lsns = [w.append(r) for r in recs]
        w.flush()
        data, last, more = w.tail_bytes(0)
        assert not more and last == lsns[-1]
        assert [r for _lsn, r in iter_frames(data)] == recs
        # a mid-stream cursor ships only the strictly-later records
        data2, last2, _ = w.tail_bytes(lsns[2])
        assert [r for _l, r in iter_frames(data2)] == recs[3:]
        assert last2 == lsns[-1]
        w.close()

    def test_tail_bytes_paginates(self, tmp_path):
        w = WAL(str(tmp_path / "wal.log"), segment_bytes=96)
        recs = [("import_bits", "f", [i], [i]) for i in range(6)]
        for r in recs:
            w.append(r)
        w.flush()
        got, since, rounds = [], 0, 0
        while True:
            data, last, more = w.tail_bytes(since, max_bytes=64)
            got.extend(r for _l, r in iter_frames(data))
            rounds += 1
            since = last
            if not more:
                break
        assert got == recs and rounds > 1
        w.close()

    def test_iter_frames_rejects_corrupt_stream(self):
        with pytest.raises(ValueError):
            list(iter_frames(b"\x00" * 20))


# -- record shard filtering ---------------------------------------------------


class TestRecordFiltering:
    def test_record_shards(self):
        W = SHARD_WIDTH
        # set_bit records are (op, field, row, col, ts) — col at [3]
        assert record_shards(("set_bit", "f", 3, W + 1, None), W) == {1}
        assert record_shards(("clear_bit", "f", 3, 2 * W), W) == {2}
        assert record_shards(("import_bits", "f", [1, 2], [0, 2 * W]), W) \
            == {0, 2}
        assert record_shards(("set_values", "f", [0, W], [7, 8]), W) == {0, 1}
        assert record_shards(("row_plane", "f", b"", 5), W) == {5}
        assert record_shards(("clear_value", "f", W + 3), W) == {1}
        assert record_shards(("df_changeset", "t", 2, {}), W) == {2}
        assert record_shards(("delete_field", "f"), W) is None

    def test_filter_record_subsets_pairwise(self):
        W = SHARD_WIDTH
        rec = ("import_bits", "f", [1, 2, 3], [0, W, 2 * W])
        out = filter_record(rec, lambda s: s == 1, W)
        assert out == ("import_bits", "f", [2], [W])
        rec2 = ("set_values", "f", [0, W], [7, 8])
        assert filter_record(rec2, lambda s: s == 0, W) \
            == ("set_values", "f", [0], [7])
        assert filter_record(rec, lambda s: s == 9, W) is None
        # index-wide records always pass
        assert filter_record(("clear_row", "f", "", 3), lambda s: False, W) \
            == ("clear_row", "f", "", 3)


# -- checkpoint metadata ------------------------------------------------------


class TestCheckpointMeta:
    def test_roundtrip_and_missing(self, tmp_path):
        assert read_checkpoint_meta(str(tmp_path)) == 0
        assert read_checkpoint_meta(None) == 0
        write_checkpoint_meta(str(tmp_path), 42)
        assert read_checkpoint_meta(str(tmp_path)) == 42
        write_checkpoint_meta(str(tmp_path), 43)  # atomic replace
        assert read_checkpoint_meta(str(tmp_path)) == 43

    def test_checkpoint_stamps_lsn_and_prunes(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.import_bits("i", "f", rows=[0, 1], cols=[3, 9])
        idx = api.holder.index("i")
        assert idx.wal.record_bytes > 0
        api.save()  # checkpoint: snapshot + meta + prune
        assert idx.wal.record_bytes == 0
        meta = os.path.join(api.holder._index_path("i"), CHECKPOINT_META)
        assert os.path.isfile(meta)
        assert read_checkpoint_meta(api.holder._index_path("i")) \
            == idx.wal.last_lsn

    def test_recovery_replays_only_above_checkpoint(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("i")
        api.create_field("i", "f")
        api.import_bits("i", "f", rows=[0], cols=[1])
        api.save()
        api.import_bits("i", "f", rows=[1], cols=[2])  # tail, not pruned
        want = api.checksum()
        api.holder.flush_wals()
        del api
        api2 = API(str(tmp_path))
        assert api2.checksum() == want
        assert api2.query("i", "Row(f=1)")[0].columns == [2]


# -- kill-point crash injection ----------------------------------------------


def _assert_oracle_prefix(result, oracle):
    """A crash may lose unacked work, never acked work, and never leave
    a state that is not an exact committed prefix."""
    assert result["checksum"] in oracle, "recovered state not a prefix"
    k = oracle.index(result["checksum"])
    assert k >= result["acked"], \
        f"acked batch lost: recovered prefix {k} < acked {result['acked']}"


class TestCrashInjection:
    # 5 sites x 6 hit counts (checkpoint-per-commit arms the savez and
    # checkpoint sites) + 6 pure-WAL points below = 36 kill points.
    @pytest.mark.parametrize("site", CRASH_SITES)
    @pytest.mark.parametrize("at", [1, 2, 3, 4, 5, 6])
    def test_kill_point_matrix(self, tmp_path, site, at):
        batches = crash_workload(n_batches=6)
        oracle = oracle_checksums(str(tmp_path), batches)
        plan = CrashPlan().kill(site, at=at)
        res = run_crash_point(str(tmp_path), plan, batches,
                              checkpoint_bytes=1)
        _assert_oracle_prefix(res, oracle)
        if not res["crashed"]:  # the site never reached its hit count
            assert res["checksum"] == oracle[-1]

    @pytest.mark.parametrize("site", ["wal.append", "wal.flush"])
    @pytest.mark.parametrize("at", [1, 2, 3])
    def test_kill_point_no_checkpoint(self, tmp_path, site, at):
        """The WAL sites again, without per-commit checkpoints: the tail
        alone must carry recovery."""
        batches = crash_workload(n_batches=6, seed=1)
        oracle = oracle_checksums(str(tmp_path), batches)
        res = run_crash_point(str(tmp_path), CrashPlan().kill(site, at=at),
                              batches)
        assert res["crashed"]
        _assert_oracle_prefix(res, oracle)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
    def test_seeded_crash_points(self, tmp_path, seed):
        """Seed-derived plans (the tier1.sh crash lane dialect): same
        seed, same kill point, forever."""
        batches = crash_workload(n_batches=6, seed=seed)
        oracle = oracle_checksums(str(tmp_path), batches)
        plan = CrashPlan.seeded(seed)
        assert plan._arms == CrashPlan.seeded(seed)._arms  # deterministic
        res = run_crash_point(str(tmp_path), plan, batches,
                              checkpoint_bytes=1)
        _assert_oracle_prefix(res, oracle)

    def test_env_seeded_plan(self, tmp_path):
        """The crash lane sets PILOSA_TPU_CRASH_SEED; default runs use a
        fixed fallback so the test always exercises a real plan."""
        plan = CrashPlan.from_env() or CrashPlan.seeded("lane-default")
        batches = crash_workload(n_batches=6, seed=9)
        oracle = oracle_checksums(str(tmp_path), batches)
        res = run_crash_point(str(tmp_path), plan, batches,
                              checkpoint_bytes=1)
        _assert_oracle_prefix(res, oracle)

    def test_from_env_parses(self, monkeypatch):
        monkeypatch.delenv("PILOSA_TPU_CRASH_SEED", raising=False)
        assert CrashPlan.from_env() is None
        monkeypatch.setenv("PILOSA_TPU_CRASH_SEED", "7")
        plan = CrashPlan.from_env()
        assert plan is not None and plan._arms == CrashPlan.seeded("7")._arms

    def test_dead_plan_noops_instead_of_rearming(self):
        plan = CrashPlan().kill("wal.append", at=1)
        with pytest.raises(SimulatedCrash):
            plan.fire("wal.append")
        assert plan.dead and plan.fired == ("wal.append", 1)
        # the dead 'process' performs no IO: every later fire says skip
        assert plan.fire("wal.append") is False
        assert plan.fire("wal.flush") is False

    def test_abandon_holder_loses_buffered_bytes(self, tmp_path):
        """The harness's crash fidelity: unflushed python-buffered bytes
        must NOT survive abandon + reopen (a plain close would flush)."""
        api = API(str(tmp_path))
        api.create_index("ci", {"trackExistence": False})
        api.create_field("ci", "f")
        api.save()
        idx = api.holder.index("ci")
        idx.wal.sync = "never"  # keep bytes in the BufferedWriter
        with api.holder.write_lock:
            idx.wal.append(("set_bit", "f", "", 0, 1))
        abandon_holder(api.holder)
        api2 = API(str(tmp_path))
        assert api2.query("ci", "Row(f=0)")[0].columns == []


# -- replay idempotence -------------------------------------------------------


class TestReplayIdempotence:
    def _source(self, path):
        api = API(path)
        api.create_index("i", {"keys": True})
        api.create_field("i", "f")
        api.create_field("i", "b", {"type": "int", "min": 0, "max": 1000})
        api.import_bits("i", "f", rows=[0, 1, 0], cols=[3, 9, SHARD_WIDTH])
        api.query("i", "Clear(9, f=1)")
        api.import_values("i", "b", cols=[3, 9], values=[10, 20])
        api.query("i", "Clear(9, b=20)")
        api.import_bits("i", "f", rows=[2], col_keys=["k1"])  # translate
        api.holder.flush_wals()
        return api

    @pytest.mark.parametrize("times", [1, 2, 3])
    def test_same_tail_applied_n_times_is_identical(self, tmp_path, times):
        src = self._source(str(tmp_path / "src"))
        recs = list(src.holder.index("i").wal.records())
        assert len(recs) >= 5

        replica = API(str(tmp_path / f"rep{times}"))
        replica.create_index("i", {"keys": True})
        replica.create_field("i", "f")
        replica.create_field("i", "b", {"type": "int", "min": 0,
                                        "max": 1000})
        idx = replica.holder.index("i")
        checks = []
        for _ in range(times):
            with replica.holder.write_lock:
                n = replica.holder.replay_records(idx, recs)
            assert n == len(recs)
            checks.append(replica.checksum())
        assert len(set(checks)) == 1, "replay is not idempotent"
        # and the planes match the source bit-for-bit
        for pql in ("Row(f=0)", "Row(f=1)", "Row(f=2)", "Row(b > 5)"):
            assert replica.query("i", pql)[0].columns == \
                src.query("i", pql)[0].columns


# -- configuration ------------------------------------------------------------


class TestRecoveryConfig:
    def test_toml_section_and_env_override(self, tmp_path):
        cfg_file = tmp_path / "pt.toml"
        cfg_file.write_text(
            "[storage.recovery]\n"
            "segment-bytes = 8192\n"
            "checkpoint-interval-bytes = 4096\n"
            "catchup-batch-bytes = 2048\n")
        cfg = Config.from_sources(toml_path=str(cfg_file), env={})
        assert cfg.storage_recovery_segment_bytes == 8192
        assert cfg.storage_recovery_checkpoint_interval_bytes == 4096
        assert cfg.storage_recovery_catchup_batch_bytes == 2048
        cfg2 = Config.from_sources(
            toml_path=str(cfg_file),
            env={"PILOSA_TPU_STORAGE_RECOVERY_SEGMENT_BYTES": "123",
                 "PILOSA_TPU_STORAGE_RECOVERY_CATCHUP_BATCH_BYTES": "77"})
        assert cfg2.storage_recovery_segment_bytes == 123  # env wins
        assert cfg2.storage_recovery_catchup_batch_bytes == 77
        assert cfg2.storage_recovery_checkpoint_interval_bytes == 4096

    def test_defaults(self):
        cfg = Config.from_sources(env={})
        assert cfg.storage_recovery_segment_bytes == 4 << 20
        assert cfg.storage_recovery_checkpoint_interval_bytes == 0
        assert cfg.storage_recovery_catchup_batch_bytes == 1 << 20

    def test_manager_from_config(self, tmp_path):
        with LocalCluster(1, base_path=str(tmp_path)) as c:
            cfg = Config.from_sources(
                env={"PILOSA_TPU_STORAGE_RECOVERY_CATCHUP_BATCH_BYTES":
                     "4096"})
            rm = RecoveryManager.from_config(c.nodes[0], cfg)
            assert rm.batch_bytes == 4096
            rm2 = RecoveryManager.from_config(c.nodes[0], cfg,
                                              batch_bytes=99)
            assert rm2.batch_bytes == 99  # explicit override wins


# -- replica catch-up ---------------------------------------------------------


def _lag_node2(c):
    """Schema + an initial replicated write, then writes that land only
    on node0/node1 (node2 'was down' for them)."""
    c.coordinator.create_index("i")
    c.coordinator.create_field("i", "f")
    c.coordinator.import_bits("i", "f", rows=[0, 1, 2, 0],
                              cols=[1, 5, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 1])
    c.run_gossip_rounds(2)
    for n in c.nodes[:2]:
        n.api.import_bits("i", "f", rows=[3, 3, 1],
                          cols=[7, SHARD_WIDTH + 2, 9])
        n._announce_shards("i")
    c.run_gossip_rounds(3)


class TestReplicaCatchUp:
    def test_lagging_detects_strictly_ahead_peers(self, tmp_path):
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery()
            _lag_node2(c)
            lag = rm.lagging("i")
            assert set(lag) == {"node0", "node1"}
            assert all(shards for shards in lag.values())
            # up-to-date nodes see no lag anywhere
            rm0 = c.nodes[0].enable_recovery()
            assert rm0.lagging("i") == {}

    def test_catch_up_converges_bit_identically(self, tmp_path):
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery()
            _lag_node2(c)
            assert c.nodes[2].api.checksum() != c.nodes[0].api.checksum()
            summary = rm.catch_up()
            assert summary["shards"] > 0
            sums = [n.api.checksum() for n in c.nodes]
            assert sums[0] == sums[1] == sums[2]
            assert c.nodes[2].query("i", "Row(f=3)")[0].columns == \
                [7, SHARD_WIDTH + 2]
            # second run: nothing left to repair
            again = rm.catch_up()
            assert again["shards"] == 0 and again["indexes"] == []

    def test_catch_up_under_injected_faults(self, tmp_path):
        """Dropped + delayed recovery RPCs are absorbed by the client's
        retry/backoff; catch-up still converges."""
        plan = (FaultPlan(seed=3)
                .drop("node0", first=0, count=1, op="recovery")
                .delay("node0", 0.01, first=1, count=2, op="recovery")
                .drop("node1", first=0, count=1, op="recovery"))
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path),
                          fault_plan=plan) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery()
            _lag_node2(c)
            summary = rm.catch_up()
            assert summary["shards"] > 0
            sums = [n.api.checksum() for n in c.nodes]
            assert sums[0] == sums[1] == sums[2]

    def test_writes_queue_during_catch_up_and_drain_after(self, tmp_path):
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            c.coordinator.create_index("i")
            c.coordinator.create_field("i", "f")
            rm = c.nodes[2].enable_recovery()
            rm.begin("i")
            # a forwarded write arriving mid-catch-up must queue, not apply
            n = c.nodes[2].import_bits("i", "f", rows=[5], cols=[6],
                                       remote=True)
            assert n == 0
            assert c.nodes[2].api.query("i", "Row(f=5)")[0].columns == []
            assert rm.drain() == 1
            assert c.nodes[2].api.query("i", "Row(f=5)")[0].columns == [6]
            # drained: the next remote write applies immediately
            c.nodes[2].import_bits("i", "f", rows=[5], cols=[8],
                                   remote=True)
            assert c.nodes[2].api.query("i", "Row(f=5)")[0].columns == [6, 8]

    def test_catch_up_gossips_breaker_open_then_closed(self, tmp_path):
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery()
            _lag_node2(c)
            states = []
            orig = c.nodes[2].gossip.record_breaker

            def spy(node_id, state, **kw):
                states.append((node_id, state))
                return orig(node_id, state, **kw)

            c.nodes[2].gossip.record_breaker = spy
            rm.catch_up()
            assert ("node2", "open") in states
            assert ("node2", "closed") in states
            assert states.index(("node2", "open")) < \
                states.index(("node2", "closed"))

    def test_drain_is_per_index(self, tmp_path):
        """Regression: drain() used to clear the WHOLE active set and a
        single shared queue, so overlapping catch-up runs on different
        indexes released each other's deferred writes mid-replay."""
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            for name in ("i", "j"):
                c.coordinator.create_index(name)
                c.coordinator.create_field(name, "f")
            rm = c.nodes[2].enable_recovery()
            rm.begin("i")
            rm.begin("j")
            assert c.nodes[2].import_bits("i", "f", rows=[1], cols=[2],
                                          remote=True) == 0
            assert c.nodes[2].import_bits("j", "f", rows=[3], cols=[4],
                                          remote=True) == 0
            assert rm.drain(["i"]) == 1  # only i's queue applies
            assert not rm.active("i") and rm.active("j")
            assert c.nodes[2].api.query("i", "Row(f=1)")[0].columns == [2]
            assert c.nodes[2].api.query("j", "Row(f=3)")[0].columns == []
            assert rm.drain() == 1  # bare drain still releases the rest
            assert c.nodes[2].api.query("j", "Row(f=3)")[0].columns == [4]

    def test_failed_catch_up_keeps_breaker_open(self, tmp_path):
        """Regression: catch_up's finally used to gossip 'closed' even
        when repair raised, so a still-lagging node advertised itself
        caught up and peers routed reads back to stale data. Failure
        must propagate and leave the breaker open; a retry that
        completes closes it."""
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery()
            _lag_node2(c)
            states = []
            orig = c.nodes[2].gossip.record_breaker

            def spy(node_id, state, **kw):
                states.append((node_id, state))
                return orig(node_id, state, **kw)

            c.nodes[2].gossip.record_breaker = spy

            def unreachable(index, origin, shards):
                raise OSError("peer unreachable")

            rm._repair_from = unreachable
            with pytest.raises(OSError):
                rm.catch_up()
            assert ("node2", "open") in states
            assert ("node2", "closed") not in states
            del rm._repair_from  # retry with the real repair path
            summary = rm.catch_up()
            assert summary["shards"] > 0
            assert ("node2", "closed") in states
            sums = [n.api.checksum() for n in c.nodes]
            assert sums[0] == sums[1] == sums[2]

    def test_recovery_endpoints_ship_snapshot_and_tail(self, tmp_path):
        """The transport itself: /internal/recovery/snapshot returns an
        installable npz + LSN; /internal/recovery/wal ships CRC-framed
        records above a cursor."""
        import base64

        with LocalCluster(2, replica_n=2, base_path=str(tmp_path)) as c:
            c.coordinator.create_index("i")
            c.coordinator.create_field("i", "f")
            c.coordinator.import_bits("i", "f", rows=[0, 1], cols=[3, 9])
            peer = c.nodes[0].node
            client = c.nodes[1].client
            snap = client.recovery_snapshot(peer, "i", 0)
            assert snap["lsn"] > 0 and snap["npz"]
            tail = client.recovery_wal(peer, "i", 0, 1 << 20)
            frames = base64.b64decode(tail["frames"])
            recs = [r for _lsn, r in iter_frames(frames)]
            assert any(r[0] == "import_bits" for r in recs)
            assert tail["last_lsn"] == snap["lsn"] and not tail["more"]
            # a cursor at the tip ships nothing
            empty = client.recovery_wal(peer, "i", tail["last_lsn"], 1 << 20)
            assert base64.b64decode(empty["frames"]) == b""


# -- metrics ------------------------------------------------------------------


class TestRecoveryMetrics:
    def test_checkpoint_and_catchup_metrics_exposed(self, tmp_path):
        api = API(str(tmp_path / "a"))
        api.create_index("i")
        api.create_field("i", "f")
        api.import_bits("i", "f", rows=[0], cols=[1])
        base = M.REGISTRY.summary(M.METRIC_RECOVERY_CHECKPOINT_SECONDS)[0]
        api.save()
        assert M.REGISTRY.summary(
            M.METRIC_RECOVERY_CHECKPOINT_SECONDS)[0] == base + 1
        text = M.REGISTRY.prometheus_text()
        assert "recovery_checkpoint_seconds" in text

    def test_catch_up_counts_shards_and_lag(self, tmp_path):
        reg = M.MetricsRegistry()
        with LocalCluster(3, replica_n=3, base_path=str(tmp_path)) as c:
            c.enable_gossip()
            rm = c.nodes[2].enable_recovery(registry=reg)
            _lag_node2(c)
            rm.catch_up()
            assert reg.value(M.METRIC_RECOVERY_CATCHUP_SHARDS) > 0
            h = reg.histogram(M.METRIC_RECOVERY_CATCHUP_LAG_MS)
            assert h is not None and h["count"] == 1
