"""Bounded-structure churn audit (ISSUE 19 satellite).

Every per-tenant / per-key table in the serving path has a hard cap so
a hostile or merely huge ID stream cannot grow resident state without
bound. This suite churns 10^5 distinct IDs (or enough distinct keys to
overflow the smaller module-level caches several times over) through
each structure and asserts the cap held, the overflow path engaged,
and the structure still answers sanely afterwards — the unit-level
twin of bench.py config 22's post-soak cap sweep.
"""

import pytest

from pilosa_tpu.cache.result_cache import ResultCache
from pilosa_tpu.errors import QuotaExceededError
from pilosa_tpu.loadgen.tenants import SyntheticTenants
from pilosa_tpu.obs.flight import FlightRecorder
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.obs.slo import SLOTracker
from pilosa_tpu.obs.tenants import OVERFLOW_TENANT, TenantRegistry
from pilosa_tpu.obs.tracing import Span, TraceStore
from pilosa_tpu.sched import ManualClock, QueryScheduler
from pilosa_tpu.sched.scheduler import _Pending

CHURN = 100_000


class TestTenantRegistryChurn:
    def test_stats_table_caps_at_max_tracked(self):
        reg = TenantRegistry(max_tracked=64, registry=MetricsRegistry())
        pop = SyntheticTenants(CHURN)
        for tid in pop.all_ids():
            reg.note(tid, queries=1)
        # tracked cells + the single overflow cell, never one more
        assert len(reg._stats) <= reg.max_tracked + 1
        assert OVERFLOW_TENANT in reg._stats
        assert reg._dropped > 0
        # the overflow cell absorbed everything past the cap
        overflow = reg._stats[OVERFLOW_TENANT]
        assert overflow.queries >= CHURN - reg.max_tracked
        # the registry still publishes a sane snapshot afterwards
        snap = reg.stats_json()
        assert snap["tracked"] <= reg.max_tracked + 1
        assert snap["dropped"] == reg._dropped
        assert OVERFLOW_TENANT in snap["tenants"]

    def test_token_bucket_tables_stay_bounded(self):
        clock = ManualClock()
        reg = TenantRegistry(max_tracked=16, default_qps=1e9,
                             default_ingest_rows_s=1e9,
                             clock=clock.now, registry=MetricsRegistry())
        pop = SyntheticTenants(CHURN)
        for tid in pop.all_ids():
            reg.charge_query(tid)
            reg.charge_ingest(tid, rows=1)
        # hostile-ID bound: the tables clear past 4x max_tracked, so
        # they can never hold more than that plus the current insert
        assert len(reg._qps) <= 4 * reg.max_tracked + 1
        assert len(reg._ingest) <= 4 * reg.max_tracked + 1
        # quotas still enforce after the churn
        tight = TenantRegistry(max_tracked=16, default_qps=1.0,
                               clock=clock.now,
                               registry=MetricsRegistry())
        tight.charge_query("t0")
        with pytest.raises(QuotaExceededError) as ei:
            for _ in range(64):
                tight.charge_query("t0")
        assert ei.value.retry_after_s > 0


class TestSLOTenantChurn:
    def test_tenant_dimension_caps_with_overflow_cell(self):
        clock = ManualClock()
        tracker = SLOTracker(clock=clock, registry=MetricsRegistry())
        pop = SyntheticTenants(CHURN)
        for tid in pop.all_ids():
            tracker.record("query", 1.0, tenant=tid)
        # the set holds at most cap distinct IDs plus "__other__"
        assert len(tracker._tenant_ids) <= tracker.tenant_cap + 1
        assert "__other__" in tracker._tenant_ids
        rows = tracker.tenant_burn_rates()
        assert len({r["tenant"] for r in rows}) <= tracker.tenant_cap + 1


class TestSchedulerVtimeChurn:
    def test_vtime_table_clears_past_bound(self):
        from pilosa_tpu.pql.parser import parse

        sched = QueryScheduler(executor=object(), fair_share=True)
        pop = SyntheticTenants(CHURN)
        q = parse("Count(Row(f=1))")
        for i, tid in enumerate(pop.all_ids()):
            p = _Pending("i", q, None, "interactive", None, 0.0, i)
            p.tenant = tid
            sched._assign_vtime_locked(p)
            assert len(sched._tenant_vtime) <= 256
            # the vclock floor keeps post-clear vtimes monotone
            assert p.vtime >= sched._vclock


class TestTraceStoreChurn:
    def test_trace_store_evicts_oldest(self):
        reg = MetricsRegistry()
        store = TraceStore(capacity=64, registry=reg)
        last_ids = []
        for i in range(10_000):
            root = Span(f"q{i}")
            root.duration_s = 0.001
            store.add(root)
            last_ids.append(root.trace_id)
        assert len(store._traces) <= store.capacity
        # newest-first listing survives, oldest got evicted
        listed = {d["traceID"] for d in store.list()}
        assert listed == set(last_ids[-64:])


class TestFlightChurn:
    def test_event_ring_and_bundle_ring_bounded(self):
        clock = ManualClock()
        fl = FlightRecorder(capacity=4, cooldown_s=0.0,
                            registry=MetricsRegistry(), clock=clock)
        for i in range(CHURN):
            fl.record_event("churn", i=i)
        assert len(fl.events()) <= 64
        for i in range(100):
            clock.advance(1.0)
            fl.trigger(f"t{i % 8}", "churn")
        assert len(fl.summaries()) <= 4


class TestResultCacheChurn:
    def test_entry_and_byte_caps_hold(self):
        cache = ResultCache(max_entries=64, max_bytes=1 << 20,
                            registry=MetricsRegistry())
        for i in range(CHURN):
            out = cache.run(("q", i), lambda i=i: [i])
            assert out == [i]
        st = cache.stats()
        assert st["entries"] <= 64
        assert st["bytes"] <= 1 << 20
        assert st["evictions"] > 0
        # the cache still serves hits after the churn
        key = ("q", CHURN - 1)
        assert cache.run(key, lambda: ["recomputed"]) == [CHURN - 1]


class TestModuleLevelCaps:
    def test_device_zeros_cap(self):
        from pilosa_tpu.ops import bitmap as B

        planes = [B.device_zeros(8 * (i + 1)) for i in range(40)]
        assert len(B._DEVICE_ZEROS) <= B._DEVICE_ZEROS_CAP
        assert planes[-1].shape == (8 * 40,)

    def test_program_cache_cap(self, monkeypatch):
        from pilosa_tpu.parallel import mesh
        from pilosa_tpu.pql import programs as P

        # stub the compiler: this audits the cache's bound, not XLA
        monkeypatch.setattr(mesh, "compile_tape_plane",
                            lambda tape, masked: ("fn", tape))
        for i in range(P._PROGRAMS_CAP + 40):
            fn = P._program("plane", (("leaf", i),), 1, False, 8)
            assert fn == ("fn", (("leaf", i),))
        assert P.program_cache_len() <= P._PROGRAMS_CAP

    def test_mask_plane_cap(self, monkeypatch):
        from pilosa_tpu.pql import executor as X

        # stub device upload: this audits the LRU bound, not staging
        monkeypatch.setattr("pilosa_tpu.parallel.mesh.engine_put",
                            lambda plane: plane)
        for i in range(X._MASK_CAP + 20):
            X._mask_plane((i,), (i,))
        assert len(X._MASK_PLANES) <= X._MASK_CAP
