"""Distributed tracing plane (obs/tracing.py): contextvar span scopes,
traceparent propagation over internode RPC, the bounded trace store and
its /internal/traces surface, profile=true span trees, the slow-query
log, and the trace_* metrics exposition.

The cross-thread regression cases pin the two boundaries that used to
drop parentage: the scheduler's dispatch worker (span_scope restore) and
the cluster fan-out pool (full copy_context per leg — a hedged remote
leg's span must stay a child of the coordinator's query span).
"""

import json
import random
import threading
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.obs import tracing as T
from pilosa_tpu.obs.tracing import (NOP_SPAN, NopTracer, Span, TraceStore,
                                    Tracer, current_span,
                                    current_traceparent, format_traceparent,
                                    parse_traceparent, span_scope)


@pytest.fixture
def tracer():
    """An always-sampling global tracer with its own store + registry,
    restored after the test (the suite may run under the tier-1 tracing
    lane's env-bootstrapped tracer)."""
    prev = T.get_tracer()
    reg = MetricsRegistry()
    t = Tracer(enabled=True, sample_rate=1.0,
               store=TraceStore(64, registry=reg), registry=reg)
    T.set_tracer(t)
    yield t
    T.set_tracer(prev)


@pytest.fixture
def nop_global():
    """Force the disabled default tracer for profile-with-tracing-off
    cases."""
    prev = T.get_tracer()
    T.set_tracer(NopTracer())
    yield
    T.set_tracer(prev)


def _names(span_json, acc=None):
    """All span names in a to_json tree (local and remote alike)."""
    acc = acc if acc is not None else []
    acc.append(span_json.get("name", ""))
    for c in span_json.get("children", ()):
        _names(c, acc)
    return acc


def _find(span_json, name):
    """All subtree dicts with the given span name."""
    out = []
    if span_json.get("name") == name:
        out.append(span_json)
    for c in span_json.get("children", ()):
        out.extend(_find(c, name))
    return out


class TestSpanBasics:
    def test_span_tree_and_parentage(self, tracer):
        with tracer.start_trace("root", index="i") as root:
            assert current_span() is root
            with tracer.start_span("child") as child:
                assert current_span() is child
                with tracer.start_span("grand") as grand:
                    pass
            assert current_span() is root
        assert current_span() is None
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        doc = root.to_json()
        assert doc["name"] == "root"
        assert doc["tags"] == {"index": "i"}
        assert doc["duration_ns"] > 0
        assert [c["name"] for c in doc["children"]] == ["child"]
        assert tracer.registry.value(M.METRIC_TRACE_STARTED) == 1.0
        assert tracer.registry.value(M.METRIC_TRACE_FINISHED) == 1.0

    def test_record_attaches_premeasured_child(self, tracer):
        with tracer.start_trace("root") as root:
            root.record("sched.queue_wait", 0.005, priority="interactive")
        doc = root.to_json()
        (wait,) = doc["children"]
        assert wait["name"] == "sched.queue_wait"
        assert wait["duration_ns"] == 5_000_000
        assert wait["tags"] == {"priority": "interactive"}

    def test_exception_tags_error_and_unwinds(self, tracer):
        with pytest.raises(RuntimeError):
            with tracer.start_trace("root") as root:
                raise RuntimeError("boom")
        assert root.tags["error"] == "boom"
        assert current_span() is None

    def test_start_span_outside_any_trace_is_nop(self, tracer):
        # stages never create implicit roots: stray background work
        # (maintenance threads, gossip rounds) stays untraced
        assert tracer.start_span("orphan") is NOP_SPAN
        assert len(tracer.store) == 0

    def test_nested_start_trace_joins_as_child(self, tracer):
        # a profile wrapper and the query path compose into ONE trace
        with tracer.profile("query.profile") as outer:
            with tracer.start_trace("query.pql") as inner:
                pass
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id

    def test_remote_child_dict_passes_through_to_json(self, tracer):
        with tracer.start_trace("root") as root:
            root.add_remote({"name": "rpc.x", "children": []}, attempt=1)
        (sub,) = root.to_json()["children"]
        assert sub["name"] == "rpc.x"
        assert sub["tags"]["attempt"] == 1


class TestNopPath:
    def test_disabled_tracer_returns_the_one_shared_span(self):
        t = NopTracer()
        spans = {id(t.start_trace("a")), id(t.start_span("b")),
                 id(NOP_SPAN)}
        assert spans == {id(NOP_SPAN)}  # zero per-query allocations
        # the shared span is immutable and inert
        assert NOP_SPAN.set_tag("k", "v") is NOP_SPAN
        assert NOP_SPAN.record("x", 1.0) is NOP_SPAN
        assert NOP_SPAN.tags == {} and not NOP_SPAN.recording
        with NOP_SPAN as s:
            assert s is NOP_SPAN

    def test_profile_forces_a_real_span_with_tracing_off(self):
        t = NopTracer()
        with t.profile("query.profile") as root:
            with t.start_span("stage"):
                pass
        assert root is not NOP_SPAN
        assert [c["name"] for c in root.to_json()["children"]] == ["stage"]

    def test_unsampled_root_counts_and_allocates_nothing(self):
        reg = MetricsRegistry()
        t = Tracer(enabled=True, sample_rate=0.5, registry=reg,
                   rng=random.Random(7))
        real = 0
        for _ in range(40):  # finish each before the next: roots, not nests
            s = t.start_trace("q")
            real += s is not NOP_SPAN
            s.finish()
        assert 0 < real < 40  # head sampling actually splits
        assert reg.value(M.METRIC_TRACE_STARTED) == float(real)
        assert reg.value(M.METRIC_TRACE_UNSAMPLED) == float(40 - real)


class TestTraceparent:
    def test_round_trip(self):
        tid, sid = "ab" * 16, "cd" * 8
        assert parse_traceparent(format_traceparent(tid, sid, True)) == \
            (tid, sid, True)
        assert parse_traceparent(format_traceparent(tid, sid, False)) == \
            (tid, sid, False)

    @pytest.mark.parametrize("bad", [
        None, 42, "", "00-abc", "00-" + "g" * 32 + "-" + "cd" * 8 + "-01",
        "00-" + "ab" * 16 + "-" + "cd" * 4 + "-01",
        "00-" + "ab" * 16 + "-" + "cd" * 8 + "-zz",
        "0-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
    ])
    def test_malformed_is_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_current_traceparent_tracks_scope(self, tracer):
        assert current_traceparent() is None
        with tracer.start_trace("root") as root:
            tp = current_traceparent()
            assert tp == format_traceparent(root.trace_id, root.span_id)
            with tracer.start_span("child") as child:
                assert current_traceparent() == format_traceparent(
                    child.trace_id, child.span_id)
        assert current_traceparent() is None

    def test_start_remote_honours_wire_context_even_when_disabled(self):
        # the coordinator asked for this trace; the serving node records
        # under it regardless of its own local sampling config
        t = NopTracer()
        tp = format_traceparent("ab" * 16, "cd" * 8, True)
        span = t.start_remote("rpc.query", tp, node="n1")
        assert span is not NOP_SPAN
        assert span.trace_id == "ab" * 16
        assert span.parent_id == "cd" * 8
        span.finish()
        assert t.start_remote("rpc.query", "garbage") is NOP_SPAN
        unsampled = format_traceparent("ab" * 16, "cd" * 8, False)
        assert t.start_remote("rpc.query", unsampled) is NOP_SPAN


class TestCrossThreadParentage:
    def test_span_scope_restores_parentage_on_a_worker(self, tracer):
        # the scheduler-boundary idiom: capture the submitter's span,
        # restore it (span only, not the whole context) on the worker
        with tracer.start_trace("root") as root:
            got = {}

            def worker():
                assert current_span() is None  # fresh thread: no scope
                with span_scope(root):
                    with tracer.start_span("stage") as s:
                        got["span"] = s
                assert current_span() is None

            th = threading.Thread(target=worker)
            th.start()
            th.join()
        assert got["span"].trace_id == root.trace_id
        assert got["span"].parent_id == root.span_id
        assert [c["name"] for c in root.to_json()["children"]] == ["stage"]

    def test_hedged_leg_span_is_a_child_of_the_query_span(self, tracer):
        # regression for the fan-out pool boundary: a hedge leg runs on
        # a pool thread spawned mid-race, and its span must still join
        # the coordinator's trace (satellite #1)
        from pilosa_tpu.cluster.resilience import Resilience

        res = Resilience(registry=MetricsRegistry(), hedge_min_ms=1.0,
                         hedge_max_ms=1.0)

        def run_remote(node, shards, token):
            if node == "A":  # parked primary loses the race
                token.wait(10.0)
                from pilosa_tpu.cluster.client import LegCancelled
                raise LegCancelled("parked")
            return ("part", node)

        with tracer.start_trace("query.pql", index="i") as root:
            parts, failed = res.run_legs(
                {"a": [1, 2]}, {"a": "A", "b": "B"}, run_remote,
                lambda s, r: {"b": list(s)})
        assert parts == [("part", "B")] and failed == []
        doc = root.to_json()
        legs = _find(doc, "cluster.leg")
        assert len(legs) == 2  # primary + hedge, both under the root
        by_hedge = {leg["tags"]["hedge"]: leg for leg in legs}
        assert by_hedge[True]["tags"]["node"] == "b"
        assert by_hedge[True]["tags"]["hedge_won"] is True
        assert by_hedge[False]["tags"]["hedge_won"] is False
        for leg in legs:
            assert leg["traceID"] == root.trace_id
            assert leg["parentID"] == root.span_id


class TestTraceStore:
    def test_capacity_evicts_oldest_and_counts_drops(self):
        reg = MetricsRegistry()
        store = TraceStore(capacity=3, registry=reg)
        t = Tracer(enabled=True, store=store, registry=reg)
        ids = []
        for i in range(5):
            with t.start_trace(f"q{i}") as root:
                ids.append(root.trace_id)
        assert len(store) == 3
        assert reg.value(M.METRIC_TRACE_STORE_DROPPED) == 2.0
        summaries = store.list()
        assert [s["root"] for s in summaries] == ["q4", "q3", "q2"]
        assert "spans" not in summaries[0]  # list() is summaries only
        with pytest.raises(KeyError):
            store.get(ids[0])  # evicted
        assert store.get(ids[-1])["spans"]["name"] == "q4"


class TestEndToEndSingleNode:
    def test_query_trace_reaches_store_and_history(self, tracer):
        from pilosa_tpu.api import API

        api = API()
        api.create_index("t")
        api.create_field("t", "f")
        api.query("t", "Set(1, f=2)Set(3, f=2)")
        assert api.query("t", "Count(Row(f=2))") == [2]
        rec = api.history.list()[0]
        assert rec.trace_id  # request_id <-> trace_id linkage
        doc = tracer.store.get(rec.trace_id)
        assert doc["spans"]["tags"]["request_id"] == rec.request_id
        names = _names(doc["spans"])
        assert names[0] == "query.pql"
        assert "device.dispatch" in names  # the async-dispatch split
        assert "storage.wal.commit" in _names(
            tracer.store.get(api.history.list()[-1].trace_id)["spans"])

    def test_profile_true_with_tracing_globally_off(self, nop_global):
        from pilosa_tpu.api import API

        api = API()
        api.create_index("t")
        api.create_field("t", "f")
        api.query("t", "Set(1, f=2)")
        out = api.query_json("t", "Count(Row(f=2))", profile=True)
        assert out["results"] == [1]
        prof = out["profile"]
        assert prof["name"] == "query.profile"
        names = _names(prof)
        assert "query.pql" in names and "device.dispatch" in names

    def test_slow_query_log_links_request_and_trace(self, tmp_path):
        from pilosa_tpu.api import API

        prev = T.get_tracer()
        reg = MetricsRegistry()
        before = M.REGISTRY.value(M.METRIC_TRACE_SLOW_QUERIES, kind="pql")
        T.set_tracer(Tracer(enabled=True, slow_ms=0.0001,  # everything slow
                            store=TraceStore(16, registry=reg),
                            registry=reg))
        try:
            api = API()
            api.set_query_logger(str(tmp_path / "q.log"))
            api.create_index("t")
            api.create_field("t", "f")
            api.query("t", "Set(1, f=2)")
            api.query("t", "Count(Row(f=2))")
            lines = [json.loads(ln) for ln in
                     (tmp_path / "q.log").read_text().splitlines()]
            slow = [ln for ln in lines if ln["kind"] == "slow"]
            assert slow, f"no slow-query lines in {lines}"
            rec = api.history.list()[0]
            assert slow[-1]["traceID"] == rec.trace_id
            assert slow[-1]["requestID"] == rec.request_id
            # _maybe_slow_log counts on the process-global registry
            after = M.REGISTRY.value(M.METRIC_TRACE_SLOW_QUERIES, kind="pql")
            assert after >= before + 1.0
        finally:
            T.set_tracer(prev)

    def test_scheduler_stages_appear_in_trace(self, tracer):
        from pilosa_tpu.api import API

        api = API()
        api.create_index("t")
        api.create_field("t", "f")
        api.query("t", "Set(1, f=2)")
        api.enable_scheduler(window_ms=0.2)
        try:
            assert api.query("t", "Count(Row(f=2))") == [1]
        finally:
            api.disable_scheduler()
        rec = api.history.list()[0]
        names = _names(tracer.store.get(rec.trace_id)["spans"])
        assert "sched.queue_wait" in names


class TestClusterEndToEnd:
    def test_three_node_profile_collects_remote_stages(self, nop_global):
        # the acceptance scenario: profile=true on a 3-node cluster
        # returns ONE span tree whose remote legs carry the serving
        # nodes' rpc spans, with tracing globally OFF everywhere
        from pilosa_tpu.cluster import LocalCluster
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        with LocalCluster(3) as c:
            co = c.coordinator
            # shards 0/1/2 of index "prof" hash to node1/node2/node0 —
            # the fan-out has one local and two remote legs
            co.create_index("prof")
            co.create_field("prof", "f")
            for shard in range(3):
                co.import_bits("prof", "f", rows=[1, 1],
                               cols=[shard * SHARD_WIDTH,
                                     shard * SHARD_WIDTH + 5])
            co.enable_scheduler(window_ms=0.2)
            co.enable_cache()
            try:
                out = co.query_json("prof", "Count(Row(f=1))", profile=True)
            finally:
                co.disable_scheduler()
                co.disable_cache()
            assert out["results"] == [6]
            prof = out["profile"]
            names = _names(prof)
            assert "query.pql" in names
            assert "sched.queue_wait" in names  # scheduler admission
            assert "cache.lookup" in names  # cold read: counted miss
            legs = _find(prof, "cluster.leg")
            assert legs, f"no cluster.leg spans in {names}"
            rpc = _find(prof, "rpc.post_internal_query")
            assert rpc, f"no remote rpc spans shipped back in {names}"
            # remote spans are tagged with the serving node's id
            assert all(r["tags"].get("node", "").startswith("node")
                       for r in rpc)
            # attribution coverage: named stages should account for the
            # bulk of the wall time (roots pay dispatch floors, so use a
            # loose floor here; bench config 12 tracks the real number)
            total = prof["duration_ns"]
            staged = sum(c["duration_ns"] for c in prof["children"])
            assert staged > 0 and total > 0

    def test_internal_traces_endpoints(self):
        from pilosa_tpu.cluster import LocalCluster
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        prev = T.get_tracer()
        reg = MetricsRegistry()
        T.set_tracer(Tracer(enabled=True, store=TraceStore(32, registry=reg),
                            registry=reg))
        try:
            with LocalCluster(3) as c:
                co = c.coordinator
                co.create_index("prof")  # shards 0-2 span all three nodes
                co.create_field("prof", "f")
                for shard in range(3):
                    co.import_bits("prof", "f", rows=[1],
                                   cols=[shard * SHARD_WIDTH])
                assert co.query("prof", "Count(Row(f=1))") == [3]
                base = co.node.uri
                with urllib.request.urlopen(base + "/internal/traces") as r:
                    listing = json.loads(r.read())
                assert listing["enabled"]
                assert listing["traces"], "no finished traces listed"
                tid = listing["traces"][0]["traceID"]
                with urllib.request.urlopen(
                        base + f"/internal/traces/{tid}") as r:
                    doc = json.loads(r.read())
                assert doc["traceID"] == tid
                assert doc["spans"]["name"]
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        base + "/internal/traces/deadbeef")
                assert ei.value.code == 404
                # the coordinator assembled remote spans into its tree
                q = [d for d in (T.get_tracer().store.get(s["traceID"])
                                 for s in listing["traces"])
                     if d["root"] == "query.pql"]
                assert any(_find(d["spans"], "rpc.post_internal_query")
                           for d in q)
        finally:
            T.set_tracer(prev)


class TestMetricsExposition:
    def test_trace_metrics_in_prometheus_and_json(self):
        reg = MetricsRegistry()
        t = Tracer(enabled=True, store=TraceStore(8, registry=reg),
                   registry=reg)
        with t.start_trace("q") as root:
            with t.start_span("stage"):
                pass
            root.record("sched.queue_wait", 0.001)
        text = reg.prometheus_text()
        assert "trace_started_total 1" in text
        assert "trace_finished_total 1" in text
        assert 'trace_duration_ms_bucket{le="+Inf"} 1' in text
        assert 'stage="sched.queue_wait"' in text
        assert "trace_stage_latency_ms_count" in text
        doc = reg.as_json()
        assert doc["counters"]["trace_started_total"] == 1.0
        hists = doc["histograms"]
        dur = next(v for k, v in hists.items()
                   if k.startswith("trace_duration_ms"))
        assert dur["count"] == 1
        assert any(k.startswith("trace_stage_latency_ms") for k in hists)
