"""Lock tracer (analysis/locktrace.py): disabled-path zero overhead,
order-graph cycles, dispatch/io boundary checks, Condition compat, and
the breaker-listener fires-outside-the-lock regression (satellite: the
health-plane deadlock shape, asserted with held-locks introspection)."""

import threading
import time

import pytest

from pilosa_tpu.analysis import locktrace
from pilosa_tpu.cluster.resilience import (BREAKER_OPEN, CircuitBreaker)
from pilosa_tpu.sched.clock import ManualClock


def _tracked(name, reg, **kw):
    """Wrapper bound to a PRIVATE registry: deliberate violations in
    these tests must not land in the process-wide tracer (the conftest
    audit fixture fails any test that records one there)."""
    return locktrace._TrackedLock(name, reg, **kw)


# -- disabled path ----------------------------------------------------------


@pytest.mark.skipif(locktrace.ACTIVE is not None,
                    reason="tracer enabled (PILOSA_TPU_LOCKCHECK lane)")
def test_disabled_path_allocates_no_wrappers():
    before = locktrace.WRAPPER_COUNT
    lk = locktrace.tracked_lock("t.disabled")
    rl = locktrace.tracked_lock("t.disabled.r", rlock=True)
    assert locktrace.WRAPPER_COUNT == before  # bare locks, no wrapper
    assert type(lk) is type(threading.Lock())
    assert rl.__class__.__name__ == "RLock"
    assert locktrace.held_locks() == []
    assert locktrace.timeline_probe() == {"enabled": False,
                                          "violations": 0}
    rep = locktrace.report()
    assert rep["enabled"] is False and rep["violations"] == []


# -- order graph + cycles ---------------------------------------------------


def test_nested_acquire_records_edge_and_held_stack():
    reg = locktrace.LockTraceRegistry()
    a, b = _tracked("A", reg), _tracked("B", reg)
    with a:
        assert reg.held_locks() == ["A"]
        with b:
            assert reg.held_locks() == ["A", "B"]
    assert reg.held_locks() == []
    assert reg.report()["edges"] == {"A": ["B"]}
    assert reg.violations() == []


def test_ab_ba_cycle_detected_without_deadlocking():
    reg = locktrace.LockTraceRegistry()
    a, b = _tracked("A", reg), _tracked("B", reg)
    with a:
        with b:
            pass

    def reversed_order():
        with b:
            with a:
                pass

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join(5)
    vs = reg.violations(kind=locktrace.KIND_CYCLE)
    assert len(vs) == 1
    assert set(vs[0]["cycle"]) == {"A", "B"}
    # the same cycle observed again dedups
    with a:
        with b:
            pass
    assert len(reg.violations(kind=locktrace.KIND_CYCLE)) == 1


def test_three_lock_cycle_reports_full_path():
    reg = locktrace.LockTraceRegistry()
    a, b, c = _tracked("A", reg), _tracked("B", reg), _tracked("C", reg)
    for outer, inner in ((a, b), (b, c)):
        with outer:
            with inner:
                pass
    with c:
        with a:  # closes C -> A, cycle A -> B -> C -> A
            pass
    vs = reg.violations(kind=locktrace.KIND_CYCLE)
    assert len(vs) == 1
    assert set(vs[0]["cycle"]) == {"A", "B", "C"}


def test_rlock_reentry_records_one_stack_entry():
    reg = locktrace.LockTraceRegistry()
    r = _tracked("R", reg, rlock=True)
    with r:
        with r:
            assert reg.held_locks() == ["R"]
        assert reg.held_locks() == ["R"]
    assert reg.held_locks() == []


def test_condition_wrapping_keeps_bookkeeping_consistent():
    reg = locktrace.LockTraceRegistry()
    lk = _tracked("CV", reg)
    cv = threading.Condition(lk)
    held_after_wait = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            held_after_wait.append(reg.held_locks())

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(5)
    # wait()'s release/re-acquire round trip restored the held stack
    assert held_after_wait == [["CV"]]
    assert reg.held_locks() == []
    assert reg.violations() == []


# -- blocking-boundary checks -----------------------------------------------


def test_dispatch_with_lock_held_is_flagged():
    reg = locktrace.LockTraceRegistry()
    lk = _tracked("holder", reg)
    with lk:
        reg.note_dispatch("platform.guarded_call")
    vs = reg.violations(kind=locktrace.KIND_DISPATCH)
    assert len(vs) == 1 and vs[0]["locks"] == ["holder"]
    # dedup: same locks at the same site report once
    with lk:
        reg.note_dispatch("platform.guarded_call")
    assert len(reg.violations(kind=locktrace.KIND_DISPATCH)) == 1


def test_dispatch_ok_lock_is_exempt():
    reg = locktrace.LockTraceRegistry()
    guard = _tracked("dispatch", reg, rlock=True, dispatch_ok=True)
    with guard:
        reg.note_dispatch("platform.guarded_call")
    assert reg.violations() == []
    reg.note_dispatch("platform.guarded_call")  # nothing held: clean
    assert reg.violations() == []


def test_io_with_lock_held_is_flagged_unless_io_ok():
    reg = locktrace.LockTraceRegistry()
    lk = _tracked("plain", reg)
    ok = _tracked("outboxish", reg, io_ok=True)
    with ok:
        reg.note_io("cluster.client._request")
    assert reg.violations() == []
    with lk:
        reg.note_io("cluster.client._request")
    vs = reg.violations(kind=locktrace.KIND_IO)
    assert len(vs) == 1 and vs[0]["locks"] == ["plain"]


def test_violation_counts_metric():
    from pilosa_tpu.obs.metrics import METRIC_LOCK_VIOLATIONS, REGISTRY

    reg = locktrace.LockTraceRegistry()
    lk = _tracked("metered", reg)
    before = REGISTRY.value(METRIC_LOCK_VIOLATIONS,
                            kind=locktrace.KIND_DISPATCH)
    with lk:
        reg.note_dispatch("site")
    after = REGISTRY.value(METRIC_LOCK_VIOLATIONS,
                           kind=locktrace.KIND_DISPATCH)
    assert after == before + 1


def test_violation_ring_is_bounded():
    reg = locktrace.LockTraceRegistry()
    lk = _tracked("cap", reg)
    with lk:
        for i in range(locktrace.VIOLATION_CAP + 50):
            reg.note_dispatch(f"site-{i}")  # distinct keys: no dedup
    assert len(reg.violations()) == locktrace.VIOLATION_CAP


def test_report_and_probe_shapes():
    reg = locktrace.LockTraceRegistry()
    a, b = _tracked("A", reg), _tracked("B", reg)
    with a:
        with b:
            pass
    rep = reg.report()
    assert rep["enabled"] is True
    assert rep["locks"] == {"A": 1, "B": 1}
    assert rep["edges"] == {"A": ["B"]}
    probe = reg.timeline_probe()
    assert probe == {"enabled": True, "violations": 0, "cycles": 0,
                     "edges": 1}


# -- breaker listeners fire outside the lock (satellite) --------------------


@pytest.fixture
def global_tracer():
    """The process-wide tracer, enabling it for this test if the lane
    env didn't already (the breaker's own lock must be created tracked
    for held-locks introspection to see it)."""
    was_on = locktrace.ACTIVE is not None
    reg = locktrace.enable()
    yield reg
    if not was_on:
        locktrace.disable()


def test_breaker_listener_fires_outside_breaker_lock(global_tracer):
    """Deterministic two-thread interleaving of the health-plane
    deadlock shape: while a transition listener is STILL RUNNING (held
    open on an event), a second thread must be able to read breaker
    state — impossible if the listener were invoked under the breaker
    lock — and the tracer's held-locks stack inside the listener must be
    empty."""
    clock = ManualClock()
    breaker = CircuitBreaker(threshold=1, open_s=3.0, clock=clock)
    in_listener = threading.Event()
    release_listener = threading.Event()
    seen = {}

    def listener(node_id, frm, to):
        seen["held"] = locktrace.held_locks()
        seen["transition"] = (node_id, frm, to)
        in_listener.set()
        assert release_listener.wait(5), "test never released listener"

    breaker.add_listener(listener)

    t1 = threading.Thread(target=breaker.record_failure, args=("n1",))
    t1.start()
    assert in_listener.wait(5), "listener never fired"

    # interleave: a second thread reads state WHILE the listener blocks
    got = {}

    def reader():
        got["state"] = breaker.state("n1")

    t2 = threading.Thread(target=reader)
    t2.start()
    t2.join(5)
    assert not t2.is_alive(), \
        "state() blocked while a listener was in flight: listener runs " \
        "under the breaker lock"
    assert got["state"] == BREAKER_OPEN

    release_listener.set()
    t1.join(5)
    assert seen["transition"] == ("n1", "closed", "open")
    assert seen["held"] == [], \
        f"breaker lock held while listener ran: {seen['held']}"


def test_breaker_on_transition_hook_outside_lock(global_tracer):
    """Same contract for the constructor's on_transition hook, across a
    full open -> half-open -> closed walk (allow + record_success paths
    fire it too, not just record_failure)."""
    clock = ManualClock()
    held_per_event = []

    def hook(node_id, frm, to):
        held_per_event.append((to, locktrace.held_locks()))

    breaker = CircuitBreaker(threshold=1, open_s=1.0, clock=clock,
                             on_transition=hook)
    breaker.record_failure("n2")
    clock.advance(1.5)
    assert breaker.allow("n2")       # grants half-open probe
    breaker.record_success("n2")     # closes
    assert [e[0] for e in held_per_event] == ["open", "half-open",
                                              "closed"]
    assert all(held == [] for _, held in held_per_event), held_per_event
