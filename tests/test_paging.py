"""Row-block paging under an HBM budget (VERDICT r3 #2; SURVEY §7
"ragged row counts").

High-cardinality fields page into fixed-shape row blocks, built lazily
and LRU-evicted under a byte cap — where the reference's roaring adapts
per container (roaring/roaring.go:53-58). Tests shrink the block size so
paging engages at test scale; the invariants are the real ones: results
bit-identical to the unpaged oracle, budget never exceeded, evictions
rebuild transparently, stale lazy builds retry.
"""

import numpy as np
import pytest

from pilosa_tpu.core import FieldOptions, FieldType, Holder
from pilosa_tpu.core import stacked as stx
from pilosa_tpu.pql import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH


ROWS = 600          # distinct values (row ids)
SHARDS = 2
BLOCK_BYTES = 4 << 20   # -> 16-row blocks at 2 shards: 38 blocks
BUDGET_BYTES = 20 << 20  # ~5 blocks resident


@pytest.fixture
def paged_env(monkeypatch):
    monkeypatch.setattr(stx, "_BLOCK_BYTES", BLOCK_BYTES)
    monkeypatch.setattr(stx, "BUDGET", stx.DeviceBudget(BUDGET_BYTES))
    h = Holder()
    e = Executor(h)
    h.create_index("i").create_field("f")
    f = h.index("i").field("f")
    rng = np.random.default_rng(7)
    oracle = {}
    # one bulk import per shard: ~ROWS/SHARDS distinct rows per shard,
    # a few bits each (the high-cardinality shape: many rows, sparse)
    for s in range(SHARDS):
        rows, cols = [], []
        for r in range(s, ROWS, SHARDS):
            n = int(rng.integers(1, 6))
            for c in rng.integers(0, SHARD_WIDTH, n):
                rows.append(r)
                cols.append(s * SHARD_WIDTH + int(c))
                oracle.setdefault(r, set()).add(cols[-1])
        f.import_bits(rows, cols)
    return h, e, f, oracle


def test_high_cardinality_topn_under_budget(paged_env):
    h, e, f, oracle = paged_env
    top = e.execute("i", f"TopN(f, n={ROWS})")[0]
    got = {p.id: p.count for p in top.pairs}
    want = {r: len(cs) for r, cs in oracle.items()}
    assert got == want
    # the stack actually paged and stayed under the cap
    stacks = [st for inner in f._stacked_cache.values()
              for (_, st) in inner.values()]
    assert any(st.paged and st.n_blocks > 1 for st in stacks)
    assert stx.BUDGET.used <= BUDGET_BYTES
    assert stx.PAGING_STATS["evictions"] > 0, "budget never forced eviction"


def test_point_reads_touch_one_block(paged_env):
    h, e, f, oracle = paged_env
    builds0 = stx.PAGING_STATS["block_builds"]
    r = sorted(oracle)[3]
    got = e.execute("i", f"Count(Row(f={r}))")[0]
    assert got == len(oracle[r])
    assert stx.PAGING_STATS["block_builds"] - builds0 <= 2, \
        "a point read materialized more than its own block"


def test_groupby_on_paged_stack_matches_oracle(paged_env):
    h, e, f, oracle = paged_env
    h.index("i").create_field("g")
    g = h.index("i").field("g")
    rng = np.random.default_rng(8)
    g_oracle = {0: set(), 1: set()}
    for s in range(SHARDS):
        rows, cols = [], []
        for c in rng.integers(0, SHARD_WIDTH, 500):
            gr = int(c) % 2
            rows.append(gr)
            cols.append(s * SHARD_WIDTH + int(c))
            g_oracle[gr].add(cols[-1])
        g.import_bits(rows, cols)
    groups = e.execute("i", "GroupBy(Rows(g), Rows(f))")[0]
    gmap = {(grp[0].row_id, grp[1].row_id): n
            for grp, n in [(gc.group, gc.count) for gc in groups]}
    for gr in (0, 1):
        for r, cs in oracle.items():
            want = len(g_oracle[gr] & cs)
            assert gmap.get((gr, r), 0) == want, (gr, r)
    assert stx.BUDGET.used <= BUDGET_BYTES


def test_eviction_rebuilds_transparently(paged_env):
    h, e, f, oracle = paged_env
    q = f"TopN(f, n={ROWS})"
    first = {p.id: p.count for p in e.execute("i", q)[0].pairs}
    # a second full scan re-streams evicted blocks with identical results
    second = {p.id: p.count for p in e.execute("i", q)[0].pairs}
    assert first == second


def test_stale_lazy_build_raises_and_query_retries(paged_env):
    h, e, f, oracle = paged_env
    from pilosa_tpu.core.stacked import StackStale, stacked_set

    st = stacked_set(f, [0, 1], "standard")
    assert st.paged
    # find an unbuilt block, then move a member fragment past the snapshot
    unbuilt = next(i for i, b in enumerate(st._blocks) if b is None)
    f.fragment(0).set_bit(0, 99)
    with pytest.raises(StackStale):
        st._ensure_block(unbuilt)
    # the executor-level read retries against a fresh stack and succeeds
    r0 = sorted(oracle)[0]
    want = len(oracle[r0] | {99}) if r0 == 0 else len(oracle[r0])
    assert e.execute("i", f"Count(Row(f={r0}))")[0] == want


def test_appends_on_paged_stack(paged_env):
    """Streaming new rows onto an already-paged stack appends slots
    without a full rebuild and stays correct."""
    h, e, f, oracle = paged_env
    e.execute("i", f"TopN(f, n={ROWS})")  # build the paged stack
    up0 = stx.UPLOAD_STATS["count"]
    bytes0 = stx.UPLOAD_STATS["bytes"]
    for k in range(5):
        e.execute("i", f"Set({k}, f={ROWS + 1000 + k})")
        assert e.execute("i", f"Count(Row(f={ROWS + 1000 + k}))")[0] == 1
    # appends may lazily build the (new) tail block but never re-upload
    # the whole stack: bound the extra transfer to a few tail blocks
    stacks = [st for inner in f._stacked_cache.values()
              for (_, st) in inner.values()]
    block_bytes = max(st.block_rows * st.total_words * 4 for st in stacks)
    assert stx.UPLOAD_STATS["count"] - up0 <= 6, \
        "appends re-uploaded more than the tail blocks"
    assert stx.UPLOAD_STATS["bytes"] - bytes0 <= 6 * block_bytes, \
        "append transfer exceeded a few blocks' worth of bytes"


def test_write_qcx_stack_releases_budget(paged_env):
    """A stack built inside a write Qcx is request-scoped: its budget
    entries must be released (not orphaned in the LRU) and later lazy
    blocks must not charge."""
    from pilosa_tpu.core.stacked import stacked_set
    from pilosa_tpu.storage.txn import TxFactory

    h, e, f, oracle = paged_env
    e.execute("i", f"TopN(f, n={ROWS})")  # warm the cached stack
    used_before = stx.BUDGET.used
    txf = TxFactory(h)
    with txf.qcx():
        f.fragment(0).set_bit(0, 7)  # dirty the field mid-request
        st = stacked_set(f, [0, 1], "standard")
        for _ in st.iter_blocks():
            pass
        assert st.ephemeral
    assert stx.BUDGET.used <= used_before, (
        "write-qcx stack leaked budget entries")


def test_executor_retries_stack_stale_midstream(paged_env, monkeypatch):
    """A writer landing AFTER the executor fetched its stack snapshot but
    BEFORE a lazy block build must surface as StackStale and be retried
    transparently — the full-scan result includes the racing write."""
    h, e, f, oracle = paged_env
    retries0 = stx.PAGING_STATS["stale_retries"]
    orig = stx.StackedSet._ensure_block
    state = {"armed": True}

    def racing_write(self, bi):
        if state["armed"] and bi > 0 and self._blocks[bi] is None:
            state["armed"] = False
            f.fragment(0).set_bit(0, 123)  # the concurrent writer
        return orig(self, bi)

    monkeypatch.setattr(stx.StackedSet, "_ensure_block", racing_write)
    top = e.execute("i", f"TopN(f, n={ROWS})")[0]
    assert not state["armed"], "the race never fired"
    assert stx.PAGING_STATS["stale_retries"] > retries0, \
        "the mid-stream write did not trip the StackStale protocol"
    oracle.setdefault(0, set()).add(123)
    got = {p.id: p.count for p in top.pairs}
    assert got == {r: len(cs) for r, cs in oracle.items()}


def test_eviction_racing_iter_blocks_reader(paged_env):
    """A budget evictor hammering _drop_block concurrently with an
    iter_blocks()/row_counts() reader: every pass rebuilds transparently
    and stays bit-identical (no writes, so never StackStale)."""
    import threading

    from pilosa_tpu.core.stacked import stacked_set
    from pilosa_tpu.ops import bitmap as B

    h, e, f, oracle = paged_env
    st = stacked_set(f, [0, 1], "standard")
    assert st.paged and st.n_blocks > 2
    want = np.zeros(len(st.row_ids), dtype=np.int64)
    for r, cs in oracle.items():
        want[st.row_index[r]] = len(cs)
    retries0 = stx.PAGING_STATS["stale_retries"]
    builds0 = stx.PAGING_STATS["block_builds"]
    stop = threading.Event()

    def evictor():
        erng = np.random.default_rng(11)
        while not stop.is_set():
            bi = int(erng.integers(0, st.n_blocks))
            st._drop_block(bi)
            stx.BUDGET.release((st.serial, bi))

    t = threading.Thread(target=evictor)
    t.start()
    try:
        for _ in range(3):
            got = np.asarray(st.row_counts()).astype(np.int64)
            assert np.array_equal(got, want)
        total = 0
        for _, blk in st.iter_blocks():
            total += int(np.asarray(B.row_counts(blk)).sum())
        assert total == int(want.sum())
    finally:
        stop.set()
        t.join()
    assert stx.PAGING_STATS["stale_retries"] == retries0, \
        "eviction (not staleness) was under test — no writes happened"
    assert stx.PAGING_STATS["block_builds"] > builds0, \
        "the evictor never forced a rebuild"


def test_advance_under_tiny_budget_no_crash(monkeypatch):
    """_advance_set must assign _blocks before charging: an eviction
    cascade can pop the new stack's own earlier entries."""
    monkeypatch.setattr(stx, "_BLOCK_BYTES", 4 << 20)
    # budget fits ~1 block: every charge evicts the previous entries
    monkeypatch.setattr(stx, "BUDGET", stx.DeviceBudget(3 << 20))
    h = Holder()
    e = Executor(h)
    h.create_index("i").create_field("f")
    f = h.index("i").field("f")
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 100, 2000)
    cols = rng.integers(0, SHARD_WIDTH, 2000)
    f.import_bits(rows.tolist(), cols.tolist())
    top = e.execute("i", "TopN(f, n=100)")[0]
    base_total = sum(p.count for p in top.pairs)
    # advance path: a genuinely new bit between queries on the paged stack
    newcol = SHARD_WIDTH - 1
    changed = e.execute("i", f"Set({newcol}, f=3)")[0]
    top2 = e.execute("i", "TopN(f, n=100)")[0]
    assert sum(p.count for p in top2.pairs) == base_total + int(changed)
    # eviction cascades under the tiny cap never left the budget over by
    # more than the entry being inserted
    assert stx.BUDGET.used <= stx.BUDGET.cap + (4 << 20)
