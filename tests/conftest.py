"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The analog of the reference's in-process multi-node cluster harness
(reference: test/cluster.go:748 MustRunCluster boots N servers in one
process): we boot N virtual XLA CPU devices so mesh/sharding tests run
without TPU hardware.

On TPU hosts a sitecustomize hook may pre-import jax and force-select the
TPU platform before conftest runs; overriding the `jax_platforms` config
(not just the env var) is what actually keeps tests off the hardware.
Set PILOSA_TPU_TEST_REAL=1 to run the suite on a real TPU instead.
"""

import os

from pilosa_tpu.platform import ensure_virtual_devices, force_cpu_platform

ensure_virtual_devices(8)
if not os.environ.get("PILOSA_TPU_TEST_REAL"):
    force_cpu_platform()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _budget_leak_audit():
    """Post-test accounting audit (the reference's testhook auditors,
    testhook/hook.go:22: every test leaves shared registries
    consistent)."""
    yield
    from pilosa_tpu.core import stacked as _stx

    _stx.BUDGET.audit()


@pytest.fixture(autouse=True)
def _lock_discipline_audit():
    """Lock-tracer audit (scripts/tier1.sh analysis lane sets
    PILOSA_TPU_LOCKCHECK=1): after every test the process-wide lock
    tracer must show zero NEW violations — a lock-order cycle or a lock
    held across device dispatch / blocking I/O is a latent deadlock no
    matter which test's interleaving exposed it, and failing the test
    that CREATED the edge points straight at the offending call path."""
    from pilosa_tpu.analysis import locktrace

    reg = locktrace.ACTIVE
    before = len(reg.violations()) if reg is not None else 0
    yield
    if reg is None or reg is not locktrace.ACTIVE:
        return
    fresh = reg.violations()[before:]
    assert not fresh, (
        "lock-discipline violations recorded during this test: "
        + "; ".join(v["message"] for v in fresh))


@pytest.fixture(autouse=True)
def _span_leak_audit():
    """Tracing-lane leak check (scripts/tier1.sh sets PILOSA_TPU_TRACE=1):
    after every test the main thread's span scope must be empty — a span
    left unfinished would silently re-parent every later trace in the
    process."""
    yield
    if not os.environ.get("PILOSA_TPU_TRACE"):
        return
    from pilosa_tpu.obs.tracing import current_span

    leaked = current_span()
    assert leaked is None, \
        f"span {leaked.name!r} leaked out of the test's trace scope"
