"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The analog of the reference's in-process multi-node cluster harness
(reference: test/cluster.go:748 MustRunCluster boots N servers in one
process): we boot N virtual XLA CPU devices so mesh/sharding tests run
without TPU hardware. Must run before the first `import jax`.
"""

import os

# Force CPU even when the ambient env selects a TPU platform (JAX_PLATFORMS
# is preset on TPU hosts); set PILOSA_TPU_TEST_REAL=1 to run the suite on
# real hardware instead.
if not os.environ.get("PILOSA_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
