"""Kernel performance attribution plane (ISSUE 11): analytic cost
model over compiled op tapes, MFU/roofline profiles keyed on
(family, shape_bucket, mesh_epoch), per-stage ingest throughput, the
``/internal/stats/kernels`` surface, and the bench regression gate.

The invariants are the acceptance criteria: bit-identical query results
with the plane on vs off, exactly zero cost-model work while disabled,
a profile with MFU/GB/s for every compiled family on a warmed cluster,
and a comparator that passes identical runs while flagging a synthetic
20% regression.
"""

import importlib.util
import json
import pathlib
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import platform
from pilosa_tpu.api import API
from pilosa_tpu.config import Config
from pilosa_tpu.obs import devprof
from pilosa_tpu.shardwidth import SHARD_WIDTH

SHARDS = 2

# three distinct tapes -> three kernel families (two count, one plane)
QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(g=1)))",
    "Intersect(Row(f=2), Row(g=2))",
]


def _fill(target, index="dk"):
    target.create_index(index)
    target.create_field(index, "f")
    target.create_field(index, "g")
    rows, cols = [], []
    for c in range(0, SHARDS * SHARD_WIDTH, SHARD_WIDTH // 16):
        rows.append((c // 64) % 5)
        cols.append(c)
    target.import_bits(index, "f", rows=rows, cols=cols)
    target.import_bits(index, "g", rows=[r % 3 for r in rows], cols=cols)
    return index


@pytest.fixture
def profiled():
    """Plane ON with clean accumulators; restores the ambient state so
    the suite behaves identically under the PILOSA_TPU_DEVPROF=1 lane."""
    was = devprof.ENABLED
    devprof.enable()
    devprof.reset()
    yield
    devprof.reset()
    devprof.enable() if was else devprof.disable()


@pytest.fixture
def unprofiled():
    was = devprof.ENABLED
    devprof.disable()
    devprof.reset()
    yield
    devprof.enable() if was else devprof.disable()


# ---------------------------------------------------------------------------
# Analytic cost model
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_count_tape_cost(self):
        # 1 op + popcount pass = 2 word passes * 32 lanes * 1024 words;
        # 2 leaf planes read * 4B * 1024 + 8B count scalar
        assert devprof.tape_cost("count", (("and", 0, 1),), 2, False,
                                 1024) == (65536.0, 8200.0)

    def test_plane_tape_cost_counts_scratch_write(self):
        flops, hbm = devprof.tape_cost(
            "plane", (("or", 0, 1), ("and", 2, 3)), 3, False, 512)
        assert flops == 32.0 * 2 * 512
        assert hbm == 4.0 * (3 + 1) * 512  # +1 scratch write, no scalar

    def test_mask_adds_one_pass_and_one_plane(self):
        flops, hbm = devprof.tape_cost("count", (("and", 0, 1),), 2,
                                       True, 1024)
        assert flops == 32.0 * 3 * 1024   # op + mask-AND + popcount
        assert hbm == 4.0 * 3 * 1024 + 8.0

    def test_cost_evals_counter_increments(self):
        before = devprof.cost_evals()
        devprof.tape_cost("count", (("or", 0, 1),), 2, False, 64)
        assert devprof.cost_evals() == before + 1

    def test_family_name_structure(self):
        fam = devprof.family_name("count", (("and", 0, 1),), 2, False)
        assert fam.startswith("count/2l/and1#") and len(fam) > 14
        # op mix is sorted and counted; the mask is tagged
        fam2 = devprof.family_name(
            "plane", (("or", 0, 1), ("and", 2, 3), ("or", 4, 5)), 3, True)
        assert fam2.startswith("plane/3l/and1+or2/m#")
        # distinct tape structure -> distinct digest
        a = devprof.family_name("count", (("and", 0, 1),), 2, False)
        b = devprof.family_name("count", (("and", 1, 0),), 2, False)
        assert a != b

    def test_shape_bucket_next_pow2(self):
        assert devprof.shape_bucket(1) == 1
        assert devprof.shape_bucket(3) == 4
        assert devprof.shape_bucket(1024) == 1024
        assert devprof.shape_bucket(1025) == 2048

    def test_pallas_mm_cost(self):
        # C[2, 14] matmul contracting 32*4096 lanes: 2*2*14*32*4096
        # FLOPs; HBM = (2+14) packed planes * 4B * 4096 + int32 result
        flops, hbm = devprof.tape_cost(
            "pallas", (("mm", 2, 14),), 16, False, 4096)
        assert flops == 2.0 * 2 * 14 * 32 * 4096
        assert hbm == 4.0 * 16 * 4096 + 4.0 * 2 * 14

    def test_pallas_cmp_cost(self):
        # depth=13 planes x 1 constant side: (6*13*1 + 8) word-ops * 32
        # lanes * 512 words; HBM reads exists+sign+result + 13 mags
        flops, hbm = devprof.tape_cost(
            "pallas", (("cmp", 13, 1),), 15, False, 512)
        assert flops == 32.0 * (6 * 13 + 8) * 512
        assert hbm == 4.0 * (3 + 13) * 512

    def test_pallas_scatter_cost(self):
        flops, hbm = devprof.tape_cost(
            "pallas", (("scatter", 300, 8),), 2, False, 8192)
        assert flops == 32.0 * 2 * 8192   # or-merge + popcount-andnot
        assert hbm == 4.0 * 3 * 8192      # planes + updates in, merged out

    def test_pallas_unknown_family_raises(self):
        with pytest.raises(ValueError):
            devprof.tape_cost("pallas", (("bogus", 1, 1),), 1, False, 64)

    def test_pallas_family_name(self):
        fam = devprof.family_name("pallas", (("mm", 2, 14),), 16, False)
        assert fam.startswith("pallas/16l/mm1#")


# ---------------------------------------------------------------------------
# KernelProfileRegistry + IngestAccounting
# ---------------------------------------------------------------------------


class TestKernelProfileRegistry:
    def _reg(self):
        return devprof.KernelProfileRegistry()

    def test_accumulate_and_roofline_snapshot(self):
        reg = self._reg()
        ent = reg.entry_for("count", (("and", 0, 1),), 2, False, 1024, 0)
        reg.record(ent, 0.001, 0.002)
        reg.record(ent, 0.001, 0.002)
        (row,) = reg.snapshot()
        assert row["dispatches"] == 2
        assert row["device_seconds"] == pytest.approx(0.006)
        assert row["flops"] == pytest.approx(2 * 65536.0)
        assert row["hbm_bytes"] == pytest.approx(2 * 8200.0)
        assert row["mfu_pct"] > 0 and row["achieved_gbps"] > 0
        assert row["us_per_dispatch"] == pytest.approx(3000.0)
        # bitmap tapes sit below any ridge point: memory-bound
        assert row["intensity_flops_per_byte"] == pytest.approx(
            65536.0 / 8200.0, rel=1e-3)
        assert row["roofline_bound"] == "memory"

    def test_same_family_different_bucket_split(self):
        reg = self._reg()
        reg.record(reg.entry_for("count", (("and", 0, 1),), 2, False,
                                 1024, 0), 0.001, 0.0)
        reg.record(reg.entry_for("count", (("and", 0, 1),), 2, False,
                                 4096, 0), 0.002, 0.0)
        rows = reg.snapshot()
        assert len(rows) == 2
        assert {r["shape_bucket"] for r in rows} == {1024, 4096}
        # sorted by device time, biggest first
        assert rows[0]["device_seconds"] >= rows[1]["device_seconds"]

    def test_mesh_epoch_keys_profiles_apart(self):
        reg = self._reg()
        reg.record(reg.entry_for("count", (("and", 0, 1),), 2, False,
                                 1024, 0), 0.001, 0.0)
        reg.record(reg.entry_for("count", (("and", 0, 1),), 2, False,
                                 1024, 1), 0.001, 0.0)
        assert reg.profile_count() == 2

    def test_call_cache_reuses_allocations(self):
        reg = self._reg()
        args = ("count", (("and", 0, 1),), 2, False, 1024, 0)
        e1 = reg.entry_for(*args)
        allocs = reg.allocations
        assert allocs == 2  # one profile + one call-cache entry
        assert reg.entry_for(*args) is e1
        assert reg.allocations == allocs

    def test_unattributed_dispatch_lands_in_other(self):
        reg = self._reg()
        reg.record(None, 0.001, 0.002)
        assert reg.other_dispatches == 1
        assert reg.other_device_s == pytest.approx(0.003)
        assert reg.snapshot() == []  # "other" is not a kernel profile

    def test_h2d_accounting(self):
        reg = self._reg()
        reg.record_h2d(1 << 20, 0.001)
        h = reg.h2d_json()
        assert h["copies"] == 1 and h["bytes"] == 1 << 20
        assert h["achieved_gbps"] == pytest.approx(
            (1 << 20) / 0.001 / 1e9, rel=1e-3)

    def test_snapshot_limit(self):
        reg = self._reg()
        for i in range(5):
            reg.record(reg.entry_for("count", (("and", 0, 1),), 2, False,
                                     1 << (6 + i), 0), 0.001 * (i + 1), 0.0)
        assert len(reg.snapshot(limit=3)) == 3

    def test_ingest_accounting_rates(self):
        acc = devprof.IngestAccounting()
        acc.record("parse", 0.5, rows=1000)
        acc.record("parse", 0.5, rows=1000)
        acc.record("wal_commit", 0.25, nbytes=1 << 20)
        snap = acc.snapshot()
        assert snap["parse"]["rows"] == 2000
        assert snap["parse"]["batches"] == 2
        assert snap["parse"]["rows_per_s"] == pytest.approx(2000.0)
        assert snap["wal_commit"]["bytes_per_s"] == pytest.approx(
            (1 << 20) / 0.25)


# ---------------------------------------------------------------------------
# Gating: zero work disabled, attribution enabled, identical results
# ---------------------------------------------------------------------------


class TestGating:
    def test_disabled_means_zero_cost_model_work(self, unprofiled):
        api = API()
        _fill(api)
        evals = devprof.cost_evals()
        allocs = devprof.KERNELS.allocations
        for q in QUERIES:
            api.query("dk", q)
        assert devprof.cost_evals() == evals
        assert devprof.KERNELS.allocations == allocs
        assert devprof.KERNELS.profile_count() == 0
        assert platform._DISPATCH_HOOK is None
        assert platform._H2D_HOOK is None
        assert devprof.stats_json() == {"enabled": False}

    def test_enabled_attributes_every_compiled_family(self, profiled):
        api = API()
        _fill(api)
        for q in QUERIES:
            api.query("dk", q)
        rows = devprof.KERNELS.snapshot()
        # three distinct tapes -> three families, all with device time
        assert len(rows) >= 3
        kinds = {r["family"].split("/")[0] for r in rows}
        assert kinds == {"count", "plane"}
        for r in rows:
            assert r["dispatches"] > 0
            assert r["device_seconds"] > 0
            assert r["mfu_pct"] > 0
            assert r["achieved_gbps"] > 0
        s = devprof.stats_json()
        assert s["enabled"] and s["backend"]
        assert s["peak_tflops"] > 0 and s["peak_gbps"] > 0
        assert s["cost_evals"] >= 3

    def test_results_bit_identical_on_vs_off(self, unprofiled):
        api = API()
        _fill(api)
        off = [api.query_json("dk", q) for q in QUERIES]
        devprof.enable()
        try:
            on = [api.query_json("dk", q) for q in QUERIES]
        finally:
            devprof.disable()
        assert json.dumps(on, sort_keys=True) \
            == json.dumps(off, sort_keys=True)

    def test_peak_override_env(self, profiled, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_DEVPROF_PEAK_TFLOPS", "2.0")
        monkeypatch.setenv("PILOSA_TPU_DEVPROF_PEAK_GBPS", "50.0")
        assert devprof.peaks() == (2.0, 50.0)


# ---------------------------------------------------------------------------
# Hook attribution details
# ---------------------------------------------------------------------------


class TestHooks:
    def test_h2d_attributed_to_ingest_only_in_scope(self, profiled):
        host = np.zeros(1024, dtype=np.uint32)
        platform.h2d_copy(host)  # outside any ingest scope
        assert devprof.KERNELS.h2d_copies == 1
        assert "h2d_copy" not in devprof.INGEST.snapshot()
        with devprof.ingest_scope():
            platform.h2d_copy(host)
        assert devprof.KERNELS.h2d_copies == 2
        stage = devprof.INGEST.snapshot()["h2d_copy"]
        assert stage["bytes"] == host.nbytes

    def test_kernel_scope_nests_and_restores(self, profiled):
        outer = ("count", (("and", 0, 1),), 2, False, 64)
        inner = ("plane", (("or", 0, 1),), 2, False, 64)
        with devprof.kernel_scope(*outer):
            ent_outer = devprof._TLS.kernel
            with devprof.kernel_scope(*inner):
                assert devprof._TLS.kernel is not ent_outer
            assert devprof._TLS.kernel is ent_outer
        assert getattr(devprof._TLS, "kernel", None) is None


# ---------------------------------------------------------------------------
# Ingest stage accounting through the real pipeline
# ---------------------------------------------------------------------------


class TestIngestStages:
    CSV = "id,city__S,pop__I\n" + "\n".join(
        f"{i},c{i % 7},{1000 + i}" for i in range(300))

    def test_columnar_ingest_populates_stages(self, profiled, tmp_path):
        from pilosa_tpu.ingest.ingest import Ingester
        from pilosa_tpu.ingest.source import CSVSource

        api = API(str(tmp_path))  # durable: WAL commits are real
        src = CSVSource(self.CSV, inline=True)
        n = Ingester(api, "cities", src).run()
        assert n == 300
        snap = devprof.INGEST.snapshot()
        assert snap["parse"]["rows"] == 300
        assert snap["parse"]["rows_per_s"] > 0
        # city__S is keyed -> bulk translation is timed
        assert snap["key_translate"]["rows"] > 0
        assert snap["fragment_advance"]["rows"] > 0
        assert snap["wal_commit"]["bytes"] > 0
        assert snap["wal_commit"]["bytes_per_s"] > 0

    def test_batch_path_records_stages_too(self, profiled):
        from pilosa_tpu.ingest.datagen import scenario
        from pilosa_tpu.ingest.ingest import Ingester

        # record-stream sources (datagen, Kafka-style) ride the Batch
        # path: no whole-file parse stage, but fragment advance is timed
        api = API()
        Ingester(api, "cust", scenario("customer", rows=100)).run()
        snap = devprof.INGEST.snapshot()
        assert snap["fragment_advance"]["rows"] > 0

    def test_disabled_ingest_records_nothing(self, unprofiled, tmp_path):
        from pilosa_tpu.ingest.ingest import Ingester
        from pilosa_tpu.ingest.source import CSVSource

        api = API(str(tmp_path))
        Ingester(api, "cities", CSVSource(self.CSV, inline=True)).run()
        assert devprof.INGEST.snapshot() == {}


# ---------------------------------------------------------------------------
# Serving surfaces: /internal/stats/kernels + the health-plane probe
# ---------------------------------------------------------------------------


class TestServing:
    def test_stats_kernels_on_warmed_cluster(self, profiled):
        from pilosa_tpu.cluster import LocalCluster

        with LocalCluster(3) as c:
            _fill(c.coordinator)
            for _ in range(2):  # warm: second pass hits compiled programs
                for q in QUERIES:
                    c.coordinator.query("dk", q)
            uri = c.coordinator.node.uri
            with urllib.request.urlopen(
                    uri + "/internal/stats/kernels") as r:
                payload = json.loads(r.read())
        assert payload["enabled"] is True
        assert payload["ridge_flops_per_byte"] > 0
        fams = {k["family"] for k in payload["kernels"]}
        assert len(fams) >= len(QUERIES)
        for k in payload["kernels"]:
            assert k["mfu_pct"] > 0
            assert k["achieved_gbps"] > 0
            assert k["roofline_bound"] in ("memory", "compute")

    def test_stats_kernels_disabled_payload(self, unprofiled):
        from pilosa_tpu.server.http import serve

        api = API()
        srv, _ = serve(api, port=0, background=True)
        try:
            host, port = srv.server_address[:2]
            with urllib.request.urlopen(
                    f"http://{host}:{port}/internal/stats/kernels") as r:
                assert json.loads(r.read()) == {"enabled": False}
        finally:
            srv.shutdown()
            srv.server_close()

    def test_timeline_probe_rides_health_samples(self, profiled):
        api = API()
        _fill(api)
        api.enable_health(config=Config())
        for q in QUERIES:
            api.query("dk", q)
        samp = api.health.timeline.sample()
        probe = samp["probes"]["kernels"]
        assert probe["enabled"] is True
        assert probe["kernels"], probe
        assert len(probe["kernels"]) <= 8  # bundles are size-bounded
        api.disable_health()

    def test_timeline_probe_disabled(self, unprofiled):
        assert devprof.timeline_probe() == {"enabled": False}


# ---------------------------------------------------------------------------
# bench_compare: the regression gate
# ---------------------------------------------------------------------------


def _bench_compare():
    path = pathlib.Path(__file__).resolve().parents[1] / "scripts" \
        / "bench_compare.py"
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchCompare:
    @pytest.fixture(scope="class")
    def bc(self):
        return _bench_compare()

    def _base(self):
        return {
            "q_p50 (cpu)": {"metric": "q_p50 (cpu)", "value": 10.0,
                            "unit": "ms"},
            "ingest (cpu)": {"metric": "ingest (cpu)", "value": 1e6,
                             "unit": "rows/s"},
        }

    def test_identical_runs_pass(self, bc):
        rows = bc.compare(self._base(), self._base())
        assert rows and not any(r["regressed"] for r in rows)

    def test_twenty_pct_regression_flagged_both_directions(self, bc):
        worse = {k: dict(v) for k, v in self._base().items()}
        worse["q_p50 (cpu)"]["value"] = 12.0    # latency up 20%
        worse["ingest (cpu)"]["value"] = 8e5    # throughput down 20%
        rows = bc.compare(self._base(), worse)
        assert {r["metric"] for r in rows if r["regressed"]} \
            == {"q_p50", "ingest"}

    def test_improvements_and_small_drift_pass(self, bc):
        better = {k: dict(v) for k, v in self._base().items()}
        better["q_p50 (cpu)"]["value"] = 5.0    # latency halved: good
        better["ingest (cpu)"]["value"] = 1.1e6  # +10%: good
        rows = bc.compare(self._base(), better)
        assert not any(r["regressed"] for r in rows)

    def test_selftest_passes(self, bc):
        assert bc._selftest(0.15) == 0

    def test_load_profile_json_lines_and_wrapper(self, bc, tmp_path):
        lines = tmp_path / "profile.json"
        lines.write_text(
            '{"metric": "m1", "value": 1.0, "unit": "ms"}\n'
            'xla warning noise\n'
            '{"metric": "__kernels__", "profile": {}}\n')
        recs = bc.load_profile(str(lines))
        assert recs["m1"]["value"] == 1.0 and "__kernels__" in recs
        wrapper = tmp_path / "BENCH_r99.json"
        wrapper.write_text(json.dumps({
            "n": 99, "cmd": "python bench.py", "rc": 0,
            "tail": 'Platform noise\n'
                    '{"metric": "m1", "value": 2.0, "unit": "ms"}\n'
                    'DOTS_PASSED=3\n'}))
        recs = bc.load_profile(str(wrapper))
        assert recs["m1"]["value"] == 2.0

    def test_cli_exit_codes(self, bc, tmp_path):
        old = tmp_path / "old.json"
        new = tmp_path / "new.json"
        old.write_text('{"metric": "m (cpu)", "value": 10.0, '
                       '"unit": "ms"}\n')
        new.write_text('{"metric": "m (cpu)", "value": 10.5, '
                       '"unit": "ms"}\n')
        assert bc.main([str(old), str(new)]) == 0
        new.write_text('{"metric": "m (cpu)", "value": 20.0, '
                       '"unit": "ms"}\n')
        assert bc.main([str(old), str(new)]) == 1
