"""Open-loop load driver (pilosa_tpu/loadgen/).

Schedule determinism, arrival processes, scenario mixes, synthetic
tenant populations, outcome classification, the ManualClock virtual
twin, intended-send-time (coordinated-omission-free) latency, good-put
bucketing, and the ChaosSchedule fire-once contract. bench.py config 22
runs the same driver wall-clock against a live cluster.
"""

import time

import pytest

from pilosa_tpu.errors import AdmissionError, QuotaExceededError
from pilosa_tpu.loadgen import (ChaosSchedule, OpenLoopDriver,
                                ScenarioMix, SyntheticTenants)
from pilosa_tpu.loadgen.scenarios import (DEFAULT_MIX, KIND_BULK_IMPORT,
                                          KIND_INTERACTIVE, KIND_SQL)
from pilosa_tpu.sched import ManualClock


def driver(execute=lambda op: "ok", **kw):
    kw.setdefault("rate_per_s", 100.0)
    kw.setdefault("duration_s", 1.0)
    kw.setdefault("seed", 7)
    return OpenLoopDriver(execute, **kw)


class TestSchedule:
    def test_same_seed_same_schedule(self):
        a = driver(arrivals="poisson").schedule
        b = driver(arrivals="poisson").schedule
        assert [(o.op_id, o.kind, o.tenant, o.intended_t) for o in a] \
            == [(o.op_id, o.kind, o.tenant, o.intended_t) for o in b]
        c = driver(arrivals="poisson", seed=8).schedule
        assert [(o.kind, o.tenant, o.intended_t) for o in a] \
            != [(o.kind, o.tenant, o.intended_t) for o in c]

    def test_uniform_arrivals_are_evenly_spaced(self):
        sched = driver(rate_per_s=100.0, duration_s=0.5).schedule
        assert len(sched) == 50
        for i, op in enumerate(sched):
            assert op.intended_t == pytest.approx(i * 0.01)

    def test_poisson_arrivals_monotone_within_duration(self):
        sched = driver(rate_per_s=200.0, duration_s=1.0,
                       arrivals="poisson").schedule
        assert 100 < len(sched) < 320  # ~200 +- slack
        ts = [op.intended_t for op in sched]
        assert ts == sorted(ts)
        assert all(0.0 <= t < 1.0 for t in ts)

    def test_invalid_parameters_rejected(self):
        with pytest.raises(ValueError):
            driver(rate_per_s=0.0)
        with pytest.raises(ValueError):
            driver(duration_s=0.0)
        with pytest.raises(ValueError):
            driver(arrivals="bursty")


class TestScenarioMix:
    def test_weights_govern_pick_frequency(self):
        mix = ScenarioMix({KIND_INTERACTIVE: 0.9, KIND_SQL: 0.1})
        sched = driver(mix=mix, rate_per_s=1000.0).schedule
        kinds = [op.kind for op in sched]
        assert set(kinds) == {KIND_INTERACTIVE, KIND_SQL}
        frac = kinds.count(KIND_INTERACTIVE) / len(kinds)
        assert 0.85 < frac < 0.95

    def test_default_mix_covers_all_kinds(self):
        sched = driver(rate_per_s=2000.0, duration_s=2.0).schedule
        assert {op.kind for op in sched} == set(DEFAULT_MIX)

    def test_bad_mixes_rejected(self):
        with pytest.raises(ValueError):
            ScenarioMix({})
        with pytest.raises(ValueError):
            ScenarioMix({KIND_SQL: -1.0})
        with pytest.raises(ValueError):
            ScenarioMix({KIND_SQL: 0.0})


class TestSyntheticTenants:
    def test_skewed_head_and_reachable_tail(self):
        pop = SyntheticTenants(100_000, seed=3)
        picks = [pop.pick() for _ in range(5_000)]
        counts = {}
        for p in picks:
            counts[p] = counts.get(p, 0) + 1
        # rank 0 dominates any deep rank
        assert counts[pop.name(0)] > 50
        # the uniform 5% tail draw reaches past the ranked head
        assert any(int(p[1:]) >= 4096 for p in picks)

    def test_deterministic_and_bounded_names(self):
        a = SyntheticTenants(1000, seed=5)
        b = SyntheticTenants(1000, seed=5)
        assert [a.pick() for _ in range(100)] \
            == [b.pick() for _ in range(100)]
        assert a.name(7) == "t0000007"
        assert sum(1 for _ in SyntheticTenants(500).all_ids()) == 500


class TestClassification:
    def outcomes(self, execute):
        clock = ManualClock()
        rep = driver(execute, rate_per_s=10.0).run_virtual(clock)
        return rep

    def test_raw_outcome_forms(self):
        rep = self.outcomes(lambda op: None)
        assert rep.ok == rep.total == 10
        rep = self.outcomes(lambda op: "shed")
        assert rep.shed == 10
        rep = self.outcomes(
            lambda op: {"outcome": "ok", "stale": op.op_id % 2 == 0})
        assert rep.ok == 10 and rep.stale == 5

    def test_admission_error_counts_as_shed_not_error(self):
        def execute(op):
            if op.op_id % 2:
                raise AdmissionError("full", retry_after_s=1.0)
            raise QuotaExceededError("quota", retry_after_s=1.0)

        rep = self.outcomes(execute)
        assert rep.shed == 10 and rep.errors == 0

    def test_unexpected_exception_counts_as_error(self):
        rep = self.outcomes(lambda op: 1 / 0)
        assert rep.errors == 10
        assert rep.latency_quantile(0.99) == 0.0  # ok-only quantile


class TestVirtualRun:
    def test_clock_advances_to_duration_and_replays(self):
        clock = ManualClock()
        seen = []
        d = driver(lambda op: seen.append((op.op_id, clock.now())),
                   rate_per_s=10.0, duration_s=1.0)
        rep = d.run_virtual(clock)
        assert clock.now() == pytest.approx(1.0)
        assert rep.total == 10
        # each op ran exactly at its intended tick
        assert [t for _, t in seen] == pytest.approx(
            [i * 0.1 for i in range(10)])

    def test_goodput_buckets_by_intended_time(self):
        clock = ManualClock()
        d = driver(lambda op: "ok" if op.intended_t < 1.0 else "shed",
                   rate_per_s=10.0, duration_s=2.0)
        rep = d.run_virtual(clock)
        assert rep.goodput_per_s(bucket_s=1.0) == [10.0, 0.0]
        with pytest.raises(ValueError):
            rep.goodput_per_s(bucket_s=0.0)

    def test_chaos_fires_at_offsets_exactly_once(self):
        clock = ManualClock()
        fired_at = []
        chaos = (ChaosSchedule()
                 .at(0.25, lambda: fired_at.append(clock.now()), "a")
                 .at(0.75, lambda: fired_at.append(clock.now()), "b")
                 .at(0.50, lambda: 1 / 0, "boom"))
        d = driver(rate_per_s=20.0, duration_s=1.0, chaos=chaos)
        d.run_virtual(clock)
        assert chaos.pending() == 0
        # in-order, once each; the raising event is marked fired with !
        assert chaos.fired() == ["a", "boom!", "b"]
        assert fired_at[0] >= 0.25 and fired_at[1] >= 0.75

    def test_chaos_needs_plan_or_cluster(self):
        with pytest.raises(ValueError):
            ChaosSchedule().drop(0.0, "node1")
        with pytest.raises(ValueError):
            ChaosSchedule().pause(0.0, 1)


class TestOpenLoopLatency:
    def test_backlog_shows_as_latency_not_omission(self):
        # one worker, 20ms service, ops every 10ms: a closed-loop
        # generator would halve the measured rate and hide the queueing;
        # the open loop records EVERY op, with latency from the
        # intended send time growing as the backlog builds
        def execute(op):
            time.sleep(0.02)
            return "ok"

        d = driver(execute, rate_per_s=100.0, duration_s=0.3,
                   max_workers=1)
        rep = d.run()
        assert rep.total == len(d.schedule)  # nothing omitted
        p50 = rep.latency_quantile(0.50)
        p99 = rep.latency_quantile(0.99)
        assert p99 > p50 >= 0.02
        assert p99 > 0.1  # tail saw the accumulated backlog
