"""Query-history ring (obs/history.py): eviction at capacity, concurrent
begin/end safety, error records, and the request_id <-> trace_id linkage
surfaced through to_json and the /query-history endpoint.
"""

import json
import threading
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.obs.history import ExecutionRequestsAPI
from pilosa_tpu.server import serve


class TestRing:
    def test_eviction_at_capacity(self):
        h = ExecutionRequestsAPI(capacity=5)
        recs = [h.begin("i", f"q{n}", "pql") for n in range(8)]
        got = h.list()
        assert len(got) == 5
        assert [r.query for r in got] == ["q7", "q6", "q5", "q4", "q3"]
        assert h.get(recs[0].request_id) is None  # evicted
        assert h.get(recs[-1].request_id).query == "q7"

    def test_end_sets_status_and_runtime(self):
        h = ExecutionRequestsAPI()
        rec = h.begin("i", "q", "pql")
        assert rec.status == "running" and rec.runtime_ns == 0
        h.end(rec)
        assert rec.status == "complete"
        assert rec.runtime_ns >= 0 and rec.error == ""

    def test_error_records(self):
        h = ExecutionRequestsAPI()
        rec = h.begin("i", "Bad(", "pql")
        h.end(rec, error="parse error")
        got = h.get(rec.request_id)
        assert got.status == "error"
        assert got.error == "parse error"

    def test_list_returns_copies_not_live_records(self):
        h = ExecutionRequestsAPI()
        rec = h.begin("i", "q", "pql")
        snap = h.list()[0]
        h.end(rec, error="late")
        assert snap.status == "running"  # the copy is a point-in-time view

    def test_concurrent_begin_end(self):
        h = ExecutionRequestsAPI(capacity=64)
        errors = []

        def worker(n):
            try:
                for k in range(50):
                    rec = h.begin("i", f"q{n}.{k}", "pql")
                    h.end(rec, error="x" if k % 7 == 0 else None)
                    h.list()
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(n,))
                   for n in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        got = h.list()
        assert len(got) == 64
        assert all(r.status in ("complete", "error") for r in got)

    def test_to_json_carries_trace_id(self):
        h = ExecutionRequestsAPI()
        rec = h.begin("i", "Count(Row(f=1))", "pql")
        rec.trace_id = "ab" * 16
        h.end(rec)
        doc = h.get(rec.request_id).to_json()
        assert doc["traceID"] == "ab" * 16
        assert doc["requestID"] == rec.request_id
        assert doc["status"] == "complete"
        assert set(doc) == {"requestID", "index", "query", "language",
                            "startTime", "runtimeNs", "status", "error",
                            "traceID"}


class TestQueryHistoryEndpoint:
    @pytest.fixture
    def server(self):
        api = API()
        srv, _ = serve(api, port=0, background=True)
        yield api, f"http://127.0.0.1:{srv.server_address[1]}"
        srv.shutdown()

    def test_history_links_traces_over_http(self, server):
        from pilosa_tpu.obs import tracing as T

        api, base = server
        prev = T.get_tracer()
        T.set_tracer(T.Tracer(enabled=True, store=T.TraceStore(16)))
        try:
            api.create_index("h")
            api.create_field("h", "f")
            api.query("h", "Set(1, f=2)")
            api.query("h", "Count(Row(f=2))")
            with pytest.raises(Exception):
                api.query("h", "Count(Row(")  # parse error -> error record
            with urllib.request.urlopen(base + "/query-history") as r:
                docs = json.loads(r.read())
            assert len(docs) == 3
            assert docs[0]["status"] == "error" and docs[0]["error"]
            ok = [d for d in docs if d["status"] == "complete"]
            assert len(ok) == 2
            for d in ok:
                # every completed query's trace is fetchable by the id
                # the history row carries
                assert d["traceID"]
                assert T.get_tracer().store.get(d["traceID"])["spans"]
        finally:
            T.set_tracer(prev)
