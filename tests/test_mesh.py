"""Mesh collective tests on the virtual 8-device CPU mesh.

The analog of the reference's in-process multi-node cluster tests
(test/cluster.go MustRunCluster): same kernels, N devices, results must
equal the single-device oracle.
"""

import jax
import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as B
from pilosa_tpu.ops.bsi import encode_values
from pilosa_tpu.parallel import ShardPlacement, analytics_mesh

S, R, W = 8, 6, 512  # 8 shards over up to 8 devices; W divisible by 2 and 4
NBITS = W * 32


@pytest.fixture(params=[1, 2, 4])
def placement(request):
    return ShardPlacement(analytics_mesh(col_parallel=request.param))


def rand_stack(rng, s=S, r=None, density=0.05):
    shape = (s, NBITS) if r is None else (s, r, NBITS)
    raw = rng.random(shape) < density
    packed = np.packbits(raw, axis=-1, bitorder="little")
    return raw, packed.view("<u4").astype(np.uint32).reshape(*shape[:-1], W)


def test_count(rng, placement):
    raw, planes = rand_stack(rng)
    assert placement.count(placement.place(planes)) == int(raw.sum())


def test_intersect_count(rng, placement):
    ra, a = rand_stack(rng)
    rb, b = rand_stack(rng)
    got = placement.intersect_count(placement.place(a), placement.place(b))
    assert got == int((ra & rb).sum())


def test_row_counts(rng, placement):
    raw, planes = rand_stack(rng, r=R)
    got = placement.row_counts(placement.place(planes))
    np.testing.assert_array_equal(got, raw.sum(axis=(0, 2)))


def test_groupby_counts(rng, placement):
    ra, a = rand_stack(rng, r=4)
    rb, b = rand_stack(rng, r=5)
    got = placement.groupby_counts(placement.place(a), placement.place(b))
    expect = np.einsum("sgw,srw->gr", ra.astype(np.int64), rb.astype(np.int64))
    np.testing.assert_array_equal(got, expect)


def test_bsi_sum(rng, placement):
    depth = 12
    stacks, filts, total, count = [], [], 0, 0
    rng2 = np.random.default_rng(3)
    for s in range(S):
        cols = np.unique(rng2.integers(0, NBITS, 500))
        vals = rng2.integers(-2000, 2000, cols.size)
        stacks.append(encode_values(cols, vals, depth, W))
        filt = np.zeros(NBITS, bool)
        filt[cols[::2]] = True
        filts.append(np.packbits(filt, bitorder="little").view("<u4"))
        total += int(vals[::2].sum())
        count += cols[::2].size
    planes = np.stack(stacks)
    filt = np.stack(filts)
    c, per_plane = placement.bsi_sum_counts(
        placement.place(planes), placement.place(filt))
    got = sum(int(per_plane[k]) << k for k in range(depth))
    assert (c, got) == (count, total)


def test_uneven_devices_rejected():
    with pytest.raises(ValueError):
        analytics_mesh(col_parallel=3)  # 8 % 3 != 0


def test_mesh_uses_all_devices():
    mesh = analytics_mesh(col_parallel=2)
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("shards", "cols")


def test_engine_sharding_fallback_is_visible():
    """VERDICT r3 weak #7: a word axis that doesn't divide the mesh must
    not silently degrade to single-device — it logs and bumps a metric."""
    import logging

    import jax

    from pilosa_tpu.obs import metrics as M
    from pilosa_tpu.parallel import mesh as meshmod

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs a multi-device mesh")
    meshmod.set_engine_mesh(meshmod.analytics_mesh(jax.devices()))
    try:
        before = M.REGISTRY.value(M.METRIC_MESH_FALLBACK)
        logger = logging.getLogger("pilosa_tpu.mesh")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger.addHandler(handler)
        try:
            sh = meshmod.engine_sharding(2, 1234567)  # prime: divides nothing
        finally:
            logger.removeHandler(handler)
        assert sh is None
        after = M.REGISTRY.value(M.METRIC_MESH_FALLBACK)
        assert after == before + 1
        assert any("SINGLE-DEVICE" in r.getMessage() for r in records)
        # repeated fallbacks still count but only warn once per shape
        meshmod.engine_sharding(2, 1234567)
        assert M.REGISTRY.value(M.METRIC_MESH_FALLBACK) == after + 1
    finally:
        meshmod.set_engine_mesh(None)
