"""Streaming ingest subsystem tests (stream/): broker semantics, the
pipelined ingester's bit-identity against the classic Ingester oracle,
exactly-once crash/resume at every pipeline stage boundary, read-
protecting backpressure, and the satellite surfaces (rate-controlled
datagen, KafkaSource StreamConsumer protocol, HTTP push/stats,
ingest_stall flight trigger, [stream] config).

``PILOSA_TPU_CRASH_SEED`` (scripts/tier1.sh stream lane) steers the
seeded stream crash plan the same way the storage crash lane does.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.errors import AdmissionError
from pilosa_tpu.ingest.datagen import scenario
from pilosa_tpu.ingest.ingest import Ingester
from pilosa_tpu.sched.clock import ManualClock
from pilosa_tpu.storage.recovery import (
    CRASH_SITES, STREAM_CRASH_SITES, CrashPlan, SimulatedCrash,
    abandon_holder)
from pilosa_tpu.stream import (BrokerSource, PipelinedIngester,
                               StreamBroker, StreamService, chunk_columns,
                               iter_rows, make_chunk, split_tp, tp_key)

ROWS = 1200
BATCH = 200


def customer_records(rows=ROWS, seed=5):
    return list(scenario("customer", rows=rows, seed=seed).records())


def make_broker(recs, partitions=2, seed=3):
    broker = StreamBroker(partitions=partitions, seed=seed)
    broker.produce_records("t", recs)
    return broker


def pipelined_run(path, broker, schema, plan=None, group="ingest"):
    api = API(path=path)
    if plan is not None:
        api.holder.crash_plan = plan
    consumer = broker.consumer(group, ["t"])
    p = PipelinedIngester(api, "idx", consumer, schema=schema,
                          batch_rows=BATCH, plan=plan, group=group)
    return api, p


# -- broker -------------------------------------------------------------------


class TestBroker:
    def test_keys_and_offsets(self):
        b = StreamBroker(partitions=4, seed=1)
        p1, o1 = b.produce("t", {"id": 1}, key="k")
        p2, o2 = b.produce("t", {"id": 2}, key="k")
        assert p1 == p2 and o2 == o1 + 1  # keyed: stable partition
        assert b.end_offset("t", p1) == 2
        assert tp_key("t", p1) == f"t:{p1}"
        assert split_tp(tp_key("a:b", 3)) == ("a:b", 3)

    def test_unkeyed_round_robin_deterministic(self):
        def spread(seed):
            b = StreamBroker(partitions=3, seed=seed)
            return [b.produce("t", {"i": i})[0] for i in range(9)]

        assert spread(7) == spread(7)  # same seed, same assignment
        assert sorted(set(spread(7))) == [0, 1, 2]  # covers partitions

    def test_group_commit_monotonic(self):
        b = StreamBroker(partitions=1)
        b.produce_records("t", [{"i": i} for i in range(10)])
        b.commit("g", {"t:0": 7})
        b.commit("g", {"t:0": 4})  # late duplicate never regresses
        assert b.committed("g", "t", 0) == 7
        assert b.committed("other", "t", 0) == 0  # groups independent

    def test_consumer_poll_commit_resume(self):
        b = StreamBroker(partitions=2, seed=0)
        b.produce_records("t", [{"i": i} for i in range(10)])
        c = b.consumer("g", ["t"])
        got = c.poll(max_records=6)
        assert len(got) == 6
        c.commit()
        c2 = b.consumer("g", ["t"])  # new member resumes from commit
        rest = c2.poll(max_records=100)
        assert len(rest) == 4
        seen = {(r.topic, r.partition, r.offset) for r in got + rest}
        assert len(seen) == 10  # no loss, no duplicates

    def test_pause_resume_and_lag(self):
        clock = ManualClock()
        b = StreamBroker(partitions=1, clock=clock)
        b.produce_records("t", [{"i": i} for i in range(5)])
        c = b.consumer("g", ["t"])
        assert c.lag() == 5
        c.pause()
        assert c.poll(100) == [] and c.paused
        clock.advance(3.0)
        c.resume()
        assert c.paused_s() == pytest.approx(3.0)
        assert len(c.poll(100)) == 5 and c.lag() == 0


# -- pipelined ingest: bit-identity oracle ------------------------------------


class TestPipelineIdentity:
    def test_matches_classic_ingester(self, tmp_path):
        recs = customer_records()
        src = scenario("customer", rows=ROWS, seed=5)
        broker = make_broker(recs)

        api1 = API(path=str(tmp_path / "classic"))
        c1 = broker.consumer("g1", ["t"])
        n1 = Ingester(api1, "idx", BrokerSource(c1, src.schema()),
                      batch_size=BATCH).run()

        api2, p = pipelined_run(str(tmp_path / "piped"), broker,
                                src.schema(), group="g2")
        n2 = p.run()
        assert n1 == n2 == ROWS
        assert api1.checksum() == api2.checksum()
        offs = api2.holder.index("idx").stream_offsets["g2"]
        assert sum(offs.values()) == ROWS  # watermark covers every row

    def test_auto_id_records(self, tmp_path):
        # no id column: deterministic per-batch idalloc sessions
        broker = StreamBroker(partitions=1)
        broker.produce_records(
            "t", [{"color": ["red"]} for _ in range(300)])
        api = API(path=str(tmp_path))
        api.create_index("idx")
        from pilosa_tpu.core.schema import FieldOptions, FieldType
        api.holder.index("idx").create_field(
            "color", FieldOptions(type=FieldType.SET, keys=True))
        p = PipelinedIngester(api, "idx", broker.consumer("g", ["t"]),
                              id_field=None, batch_rows=100)
        assert p.run() == 300
        assert api.query("idx", "Count(Row(color=red))")[0] == 300

    def test_devprof_stage_gauges(self, tmp_path):
        # the pipeline's host/device split shows up as distinct ingest
        # stages — the overlap evidence the kernel plane reports
        from pilosa_tpu.obs import devprof

        was = devprof.ENABLED
        devprof.enable()
        devprof.INGEST.reset()
        try:
            recs = customer_records(rows=600)
            src = scenario("customer", rows=600, seed=5)
            broker = make_broker(recs)
            api, p = pipelined_run(str(tmp_path), broker, src.schema())
            p.run()
            stages = devprof.INGEST.snapshot()
            assert "parse" in stages  # host side
            assert "fragment_advance" in stages  # device side
            assert "key_translate" in stages  # host-side bulk translate
        finally:
            devprof.INGEST.reset()
            devprof.enable() if was else devprof.disable()


# -- chunked messages (the Kafka batch-per-message production shape) ----------


def chunked_broker(rows=900, chunk=100, plain_tail=0, seed=11):
    """A broker whose "t" topic carries id/city/device as chunked
    column messages (plus ``plain_tail`` single-row dicts at the end)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    city = rng.integers(0, 50, rows)
    dev = rng.integers(0, 10, rows)
    broker = StreamBroker(partitions=1, seed=seed)
    body = rows - plain_tail
    for lo in range(0, body, chunk):
        hi = min(lo + chunk, body)
        broker.produce("t", make_chunk({
            "id": list(range(lo, hi)),
            "city": city[lo:hi],  # numpy columns ride through in-process
            "device": dev[lo:hi].tolist()}))
    for i in range(body, rows):
        broker.produce("t", {"id": i, "city": int(city[i]),
                             "device": int(dev[i])})
    return broker


def int_schema():
    from pilosa_tpu.ingest.source import _parse_header

    return _parse_header(["city__IS", "device__IS"])


class TestChunkedMessages:
    def test_make_chunk_validates_lengths(self):
        with pytest.raises(ValueError):
            make_chunk({"a": [1, 2], "b": [1]})
        assert chunk_columns(make_chunk({"a": [1, 2]})) == {"a": [1, 2]}
        assert chunk_columns({"id": 1}) is None  # plain rows pass through

    def test_iter_rows_expands_chunks(self):
        rows = list(iter_rows(make_chunk({"a": [1, 2], "b": [3, 4]})))
        assert rows == [{"a": 1, "b": 3}, {"a": 2, "b": 4}]
        assert list(iter_rows({"a": 5})) == [{"a": 5}]

    def test_chunked_identity_vs_classic(self, tmp_path):
        broker = chunked_broker()
        schema = int_schema()
        api1 = API(path=str(tmp_path / "classic"))
        n1 = Ingester(api1, "idx",
                      BrokerSource(broker.consumer("g1", ["t"]), schema),
                      batch_size=BATCH).run()
        api2, p = pipelined_run(str(tmp_path / "piped"), broker, schema,
                                group="g2")
        n2 = p.run()
        assert n1 == n2 == 900
        assert api1.checksum() == api2.checksum()
        # offsets count MESSAGES, not rows: 900 rows / 100-row chunks
        offs = api2.holder.index("idx").stream_offsets["g2"]
        assert sum(offs.values()) == 9

    def test_mixed_plain_and_chunked_batch(self, tmp_path):
        # a poll that straddles the chunked body and the plain tail takes
        # the row path via iter_rows — same bits either way
        broker = chunked_broker(rows=450, chunk=100, plain_tail=50)
        schema = int_schema()
        api1 = API(path=str(tmp_path / "classic"))
        n1 = Ingester(api1, "idx",
                      BrokerSource(broker.consumer("g1", ["t"]), schema),
                      batch_size=BATCH).run()
        api2, p = pipelined_run(str(tmp_path / "piped"), broker, schema,
                                group="g2")
        assert n1 == p.run() == 450
        assert api1.checksum() == api2.checksum()

    @pytest.mark.parametrize("site", STREAM_CRASH_SITES)
    def test_chunked_crash_resume(self, tmp_path, site):
        golden_api, g = pipelined_run(str(tmp_path / "golden"),
                                      chunked_broker(), int_schema())
        g.run()
        golden = golden_api.checksum()

        broker = chunked_broker()
        plan = CrashPlan().kill(site, at=2)
        api = API(path=str(tmp_path / "crash"))
        api.holder.crash_plan = plan
        # 3 chunk messages per poll -> 3 batches, so at=2 dies mid-stream
        p = PipelinedIngester(api, "idx", broker.consumer("ingest", ["t"]),
                              schema=int_schema(), batch_rows=3, plan=plan)
        crashed = False
        try:
            p.run()
        except SimulatedCrash:
            crashed = True
        assert crashed
        abandon_holder(api.holder)
        api2 = API(path=str(tmp_path / "crash"))
        p2 = PipelinedIngester(api2, "idx", broker.consumer("ingest", ["t"]),
                               schema=int_schema(), batch_rows=BATCH)
        p2.run()
        assert api2.checksum() == golden  # zero loss, zero duplicates


# -- exactly-once crash/resume ------------------------------------------------


def _crash_then_resume(tmp_path, plan, recs, schema):
    broker = make_broker(recs)
    api, p = pipelined_run(str(tmp_path), broker, schema, plan=plan)
    crashed = False
    try:
        p.run()
    except SimulatedCrash:
        crashed = True
    abandon_holder(api.holder)
    api2 = API(path=str(tmp_path))
    c2 = broker.consumer("ingest", ["t"])
    p2 = PipelinedIngester(api2, "idx", c2, schema=schema,
                           batch_rows=BATCH)
    p2.run()
    return crashed, api2


class TestStreamCrashMatrix:
    @pytest.fixture(scope="class")
    def golden(self, tmp_path_factory):
        recs = customer_records()
        src = scenario("customer", rows=ROWS, seed=5)
        d = tmp_path_factory.mktemp("golden")
        broker = make_broker(recs)
        api, p = pipelined_run(str(d), broker, src.schema())
        p.run()
        return api.checksum()

    @pytest.mark.parametrize("site", STREAM_CRASH_SITES)
    @pytest.mark.parametrize("at", [1, 2, 3])
    def test_kill_at_stage_boundary(self, tmp_path, golden, site, at):
        recs = customer_records()
        src = scenario("customer", rows=ROWS, seed=5)
        plan = CrashPlan().kill(site, at=at)
        crashed, api2 = _crash_then_resume(tmp_path, plan, recs,
                                           src.schema())
        assert crashed, f"{site}@{at} never fired"
        # zero lost, zero duplicated rows: bit-identical to a clean run
        assert api2.checksum() == golden
        offs = api2.holder.index("idx").stream_offsets["ingest"]
        assert sum(offs.values()) == ROWS

    def test_seeded_stream_plan(self, tmp_path, golden):
        """The tier1 stream lane's seed (PILOSA_TPU_CRASH_SEED) draws a
        deterministic site/hit-count from the stream site tuple."""
        seed = int(os.environ.get("PILOSA_TPU_CRASH_SEED", "1"))
        plan = CrashPlan.stream_seeded(seed)
        again = CrashPlan.stream_seeded(seed)
        assert plan._arms == again._arms  # same seed, same kill
        assert all(s in STREAM_CRASH_SITES for s in plan._arms)
        recs = customer_records()
        src = scenario("customer", rows=ROWS, seed=5)
        crashed, api2 = _crash_then_resume(tmp_path, plan, recs,
                                           src.schema())
        assert crashed
        assert api2.checksum() == golden

    def test_storage_sites_unchanged(self):
        # the stream sites live in their OWN tuple so storage-lane
        # seeded() draws are unchanged by this subsystem existing
        assert not set(STREAM_CRASH_SITES) & set(CRASH_SITES)

    def test_checkpoint_stamps_offsets_across_prune(self, tmp_path):
        recs = customer_records(rows=600)
        src = scenario("customer", rows=600, seed=5)
        broker = make_broker(recs)
        api, p = pipelined_run(str(tmp_path), broker, src.schema())
        p.run()
        want = api.checksum()
        api.save()  # checkpoint: stamps offsets, prunes the WAL tail
        abandon_holder(api.holder)
        api2 = API(path=str(tmp_path))
        # the watermark survived the prune via checkpoint.json
        offs = api2.holder.index("idx").stream_offsets["ingest"]
        assert sum(offs.values()) == 600
        # resume sees nothing new: zero rows re-ingested, state intact
        c2 = broker.consumer("ingest", ["t"])
        p2 = PipelinedIngester(api2, "idx", c2, schema=src.schema(),
                               batch_rows=BATCH)
        assert p2.run() == 0
        assert api2.checksum() == want


# -- backpressure -------------------------------------------------------------


class TestBackpressure:
    def test_enqueue_pauses_consumer_when_full(self, tmp_path):
        recs = customer_records(rows=100)
        src = scenario("customer", rows=100, seed=5)
        broker = make_broker(recs)
        api = API(path=str(tmp_path))
        consumer = broker.consumer("g", ["t"])
        p = PipelinedIngester(api, "idx", consumer, schema=src.schema(),
                              batch_rows=10, queue_depth=1)
        p._ensure_schema()
        batch = p._prepare(consumer.poll(10))
        p._queue.put_nowait(object())  # device side "busy": queue full
        assert p.credits() == 0
        t = threading.Thread(target=p._enqueue, args=(batch,))
        t.start()
        for _ in range(500):
            if consumer.paused:
                break
            time.sleep(0.002)
        assert consumer.paused  # host blocked -> consumer paused
        p._queue.get_nowait()  # device catches up
        t.join(timeout=5)
        assert not t.is_alive() and not consumer.paused
        assert p.paused_s >= 0.0

    def test_service_push_429_when_saturated(self, tmp_path):
        api = API(path=str(tmp_path))
        svc = StreamService(api, "idx", batch_rows=10, queue_depth=1,
                            max_backlog_rows=20)
        out = svc.push([{"id": i} for i in range(19)])
        assert out["accepted"] == 19
        svc.push([{"id": 99}])  # reaches the backlog bound
        with pytest.raises(AdmissionError):
            svc.push([{"id": 100}])
        assert svc.rejected == 1 and svc.stats()["saturated"]
        svc.step()  # drain
        assert not svc.saturated()
        assert svc.push([{"id": 100}])["accepted"] == 1
        svc.close()

    def test_push_validates_records(self, tmp_path):
        api = API(path=str(tmp_path))
        svc = StreamService(api, "idx")
        with pytest.raises(ValueError):
            svc.push(["not-a-dict"])
        svc.close()

    def test_scheduler_batch_priority_keeps_read_headroom(self, tmp_path):
        # with the scheduler on, the device stage admits at batch
        # priority; reads still execute during a full-rate drain
        recs = customer_records(rows=600)
        src = scenario("customer", rows=600, seed=5)
        broker = make_broker(recs)
        api = API(path=str(tmp_path))
        api.enable_scheduler()
        try:
            c = broker.consumer("g", ["t"])
            p = PipelinedIngester(api, "idx", c, schema=src.schema(),
                                  batch_rows=100)
            assert p.run() == 600
            assert api.query("idx", "Count(All())")[0] == 600
        finally:
            api.disable_scheduler()


# -- satellite: rate-controlled datagen ---------------------------------------


class TestRateControlledDatagen:
    def test_manual_clock_zero_wall_sleeps(self):
        clock = ManualClock()
        src = scenario("customer", rows=50, seed=1, rate_rows_s=100.0,
                       clock=clock)
        t0 = time.monotonic()
        recs = list(src.records())
        wall = time.monotonic() - t0
        assert len(recs) == 50
        # virtual time advanced to the release schedule, wall time didn't
        assert clock.now() == pytest.approx(49 / 100.0)
        assert wall < 1.0

    def test_rate_deterministic(self):
        a = list(scenario("customer", rows=20, seed=9, rate_rows_s=50.0,
                          clock=ManualClock()).records())
        b = list(scenario("customer", rows=20, seed=9, rate_rows_s=50.0,
                          clock=ManualClock()).records())
        assert a == b
        # and identical to the unpaced scenario's records
        assert a == list(scenario("customer", rows=20, seed=9).records())

    def test_rate_must_be_positive(self):
        with pytest.raises(ValueError):
            scenario("customer", rows=5, rate_rows_s=0.0,
                     clock=ManualClock())


# -- satellite: KafkaSource StreamConsumer protocol ---------------------------


class _FakeMsg:
    def __init__(self, topic, partition, offset, value, key=None):
        self._t, self._p, self._o = topic, partition, offset
        self._v, self._k = value, key

    def topic(self):
        return self._t

    def partition(self):
        return self._p

    def offset(self):
        return self._o

    def value(self):
        return self._v

    def key(self):
        return self._k

    def error(self):
        return None


class _FakeTopicPartition:
    def __init__(self, topic, partition, offset=-1001):
        self.topic, self.partition, self.offset = topic, partition, offset


class _FakeConsumer:
    """confluent-kafka-shaped consumer over an in-memory log."""

    def __init__(self, conf):
        self.conf = conf
        self.log = []  # injected by the test
        self.pos = 0
        self.commits = []
        self.paused_tps = []
        self.seeks = []

    def subscribe(self, topics):
        self.topics = topics

    def poll(self, timeout=0.0):
        if self.pos >= len(self.log):
            return None
        msg = self.log[self.pos]
        self.pos += 1
        return msg

    def assignment(self):
        return [_FakeTopicPartition("t", 0)]

    def commit(self, offsets=None, asynchronous=True):
        self.commits.append(offsets)

    def committed(self, tps):
        last = self.commits[-1] if self.commits else []
        return last or [_FakeTopicPartition("t", 0, 0)]

    def seek(self, tp):
        self.seeks.append((tp.topic, tp.partition, tp.offset))
        self.pos = tp.offset

    def pause(self, tps):
        self.paused_tps = tps

    def resume(self, tps):
        self.paused_tps = []


class _FakeClient:
    Consumer = _FakeConsumer
    TopicPartition = _FakeTopicPartition


class TestKafkaSourceProtocol:
    def make(self):
        from pilosa_tpu.ingest.kafka import KafkaSource

        src = KafkaSource("b:9092", ["t"], "g",
                          ["id", "color__SS"], client=_FakeClient())
        consumer = src.connect()
        consumer.log = [
            _FakeMsg("t", 0, i, json.dumps(
                {"id": i, "color": ["red"]}).encode())
            for i in range(5)]
        return src, consumer

    def test_gate_raises_without_client(self, monkeypatch):
        import builtins

        from pilosa_tpu.ingest import kafka as K

        real = builtins.__import__

        def deny(name, *a, **k):
            if name in ("confluent_kafka", "kafka"):
                raise ImportError(name)
            return real(name, *a, **k)

        monkeypatch.setattr(builtins, "__import__", deny)
        with pytest.raises(ImportError, match="no kafka client"):
            K._kafka_client()

    def test_poll_returns_stream_records(self):
        src, _ = self.make()
        recs = src.poll(max_records=3)
        assert [r.offset for r in recs] == [0, 1, 2]
        assert recs[0].topic == "t" and recs[0].partition == 0
        assert recs[0].value == {"id": 0, "color": ["red"]}
        assert len(src.poll(max_records=10)) == 2  # the rest

    def test_commit_offsets_mapping(self):
        src, consumer = self.make()
        src.poll(max_records=5)
        src.commit({"t:0": 5})
        (tps,) = consumer.commits
        assert (tps[0].topic, tps[0].partition, tps[0].offset) == \
            ("t", 0, 5)
        assert src.committed("t", 0) == 5

    def test_seek_pause_resume(self):
        src, consumer = self.make()
        src.poll(max_records=5)
        src.seek("t", 0, 2)
        assert consumer.seeks == [("t", 0, 2)]
        assert [r.offset for r in src.poll(max_records=10)] == [2, 3, 4]
        assert not src.paused
        src.pause()
        assert src.paused and consumer.paused_tps
        src.resume()
        assert not src.paused and not consumer.paused_tps

    def test_drives_pipelined_ingester(self, tmp_path):
        # the whole point of the shared protocol: the pipelined path
        # runs a (fake) real-Kafka consumer without a broker in between
        src, _ = self.make()
        api = API(path=str(tmp_path))
        p = PipelinedIngester(api, "idx", src, schema=src.schema(),
                              batch_rows=2)
        assert p.run() == 5
        assert api.query("idx", "Count(Row(color=red))")[0] == 5


# -- satellite: HTTP push / stats ---------------------------------------------


@pytest.fixture
def stream_server():
    from pilosa_tpu.server import serve

    api = API()
    svc = api.enable_stream("idx", batch_rows=10, queue_depth=1,
                            max_backlog_rows=20)
    srv, thread = serve(api, port=0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base, api, svc
    srv.shutdown()
    api.disable_stream()


def _req(base, method, path, body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(base + path, data=data, method=method,
                               headers={"Content-Type":
                                        "application/json"})
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read())


class TestHTTPSurface:
    def test_push_and_stats(self, stream_server):
        base, api, svc = stream_server
        status, out = _req(base, "POST", "/index/idx/stream/push",
                           {"records": [{"id": 1}, {"id": 2}]})
        assert status == 200 and out["accepted"] == 2
        status, out = _req(base, "GET", "/internal/stats/stream")
        assert status == 200
        assert out["enabled"] and out["lag"] == 2
        svc.step()
        status, out = _req(base, "GET", "/internal/stats/stream")
        assert out["lag"] == 0 and out["rows"] == 2

    def test_push_429_when_saturated(self, stream_server):
        base, api, svc = stream_server
        _req(base, "POST", "/index/idx/stream/push",
             {"records": [{"id": i} for i in range(20)]})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(base, "POST", "/index/idx/stream/push",
                 {"records": [{"id": 99}]})
        assert ei.value.code == 429

    def test_push_unknown_index_404(self, stream_server):
        base, api, svc = stream_server
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(base, "POST", "/index/nope/stream/push",
                 {"records": [{"id": 1}]})
        assert ei.value.code == 404

    def test_stats_disabled(self):
        from pilosa_tpu.server import serve

        api = API()
        srv, thread = serve(api, port=0, background=True)
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            status, out = _req(base, "GET", "/internal/stats/stream")
            assert status == 200 and out == {"enabled": False}
        finally:
            srv.shutdown()


# -- satellite: ingest_stall flight trigger -----------------------------------


class TestIngestStallTrigger:
    def make_plane(self, **kw):
        from pilosa_tpu.obs.health import HealthPlane

        return HealthPlane(interval_ms=10.0, clock=ManualClock(),
                           ingest_stall_s=5.0, **kw)

    def test_fires_on_saturation(self):
        hp = self.make_plane()
        fired = hp.flight.observe({"probes": {"stream": {
            "enabled": True, "saturated": True, "paused_s": 0.0}},
            "rates": {}})
        assert [b["trigger"] for b in fired] == ["ingest_stall"]
        assert "saturated" in fired[0]["reason"]

    def test_fires_on_sustained_pause(self):
        hp = self.make_plane()
        fired = hp.flight.observe({"probes": {"stream": {
            "enabled": True, "saturated": False, "paused_s": 9.5}},
            "rates": {}})
        assert [b["trigger"] for b in fired] == ["ingest_stall"]
        assert "paused" in fired[0]["reason"]

    def test_quiet_pipeline_does_not_fire(self):
        hp = self.make_plane()
        for probe in ({"enabled": False},
                      {"enabled": True, "saturated": False,
                       "paused_s": 0.1}):
            assert hp.flight.observe(
                {"probes": {"stream": probe}, "rates": {}}) == []

    def test_stream_probe_rides_api_samples(self, tmp_path):
        api = API(path=str(tmp_path))
        api.enable_stream("idx", batch_rows=10)
        try:
            hp = api.enable_health(clock=ManualClock())
            hp.clock.advance(1.0)
            hp.timeline.maybe_sample()
            sample = hp.timeline.window(None)[-1]
            assert sample["probes"]["stream"]["enabled"]
            assert sample["probes"]["stream"]["topic"] == "ingest"
        finally:
            api.disable_health()
            api.disable_stream()

    def test_probe_disabled_without_service(self):
        api = API()
        try:
            hp = api.enable_health(clock=ManualClock())
            hp.clock.advance(1.0)
            hp.timeline.maybe_sample()
            sample = hp.timeline.window(None)[-1]
            assert sample["probes"]["stream"] == {"enabled": False}
        finally:
            api.disable_health()


# -- satellite: [stream] config -----------------------------------------------


class TestStreamConfig:
    def test_toml_section_and_env(self, tmp_path):
        from pilosa_tpu.config import Config

        p = tmp_path / "c.toml"
        p.write_text("[stream]\nenabled = true\nindex = \"events\"\n"
                     "batch_rows = 4096\nqueue_depth = 3\n"
                     "ingest_stall_s = 2.5\n")
        cfg = Config.from_sources(
            toml_path=str(p),
            env={"PILOSA_TPU_STREAM_GROUP": "workers",
                 "PILOSA_TPU_STREAM_MAX_BACKLOG_ROWS": "500"})
        assert cfg.stream_enabled and cfg.stream_index == "events"
        assert cfg.stream_batch_rows == 4096
        assert cfg.stream_queue_depth == 3
        assert cfg.stream_ingest_stall_s == 2.5
        assert cfg.stream_group == "workers"  # env wins over default
        assert cfg.stream_max_backlog_rows == 500

    def test_service_from_config(self, tmp_path):
        from pilosa_tpu.config import Config

        cfg = Config()
        cfg.stream_batch_rows = 123
        cfg.stream_queue_depth = 4
        cfg.stream_group = "g9"
        api = API(path=str(tmp_path))
        svc = api.enable_stream("idx", config=cfg)
        try:
            assert svc.ingester.batch_rows == 123
            assert svc.ingester.queue_depth == 4
            assert svc.group == "g9"
            # backlog bound defaults from batch_rows * depth * 8
            assert svc.max_backlog_rows == 123 * 4 * 8
        finally:
            api.disable_stream()

    def test_health_from_config_maps_stall(self):
        from pilosa_tpu.config import Config
        from pilosa_tpu.obs.health import HealthPlane

        cfg = Config()
        cfg.stream_ingest_stall_s = 1.25
        hp = HealthPlane.from_config(cfg, clock=ManualClock())
        assert hp.flight.ingest_stall_s == 1.25

    def test_service_background_drain(self, tmp_path):
        api = API(path=str(tmp_path))
        svc = api.enable_stream("idx", batch_rows=10)
        try:
            svc.start(interval_s=0.01)
            svc.push([{"id": i} for i in range(25)])
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if svc.ingester.rows >= 25:
                    break
                time.sleep(0.01)
            assert svc.ingester.rows == 25
            assert api.query("idx", "Count(All())")[0] == 25
        finally:
            api.disable_stream()
