"""L0 bitmap kernel tests against a numpy set-semantics oracle.

Mirrors the reference's container-op tests (reference:
roaring/roaring_test.go union/intersect/difference/xor cases) but
property-style over random column sets.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as B
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

WORDS = 1 << 10  # small plane (32768 columns) for fast tests
NBITS = WORDS * 32


def rand_cols(rng, n, nbits=NBITS):
    return np.unique(rng.integers(0, nbits, size=n))


def to_set(cols):
    return set(int(c) for c in cols)


@pytest.mark.parametrize("n", [0, 1, 100, 5000])
def test_bits_roundtrip(rng, n):
    cols = rand_cols(rng, n)
    plane = B.bits_to_plane(cols, WORDS)
    out = B.plane_to_bits(plane)
    assert to_set(out) == to_set(cols)


def test_algebra_matches_set_oracle(rng):
    a_cols = rand_cols(rng, 4000)
    b_cols = rand_cols(rng, 3000)
    a, b = B.bits_to_plane(a_cols, WORDS), B.bits_to_plane(b_cols, WORDS)
    sa, sb = to_set(a_cols), to_set(b_cols)

    cases = {
        "and": (B.plane_and, sa & sb),
        "or": (B.plane_or, sa | sb),
        "xor": (B.plane_xor, sa ^ sb),
        "andnot": (B.plane_andnot, sa - sb),
    }
    for name, (fn, expect) in cases.items():
        got = to_set(B.plane_to_bits(np.asarray(fn(a, b))))
        assert got == expect, name


def test_counts(rng):
    a_cols = rand_cols(rng, 4000)
    b_cols = rand_cols(rng, 3000)
    a, b = B.bits_to_plane(a_cols, WORDS), B.bits_to_plane(b_cols, WORDS)
    assert int(B.plane_count(a)) == len(to_set(a_cols))
    assert int(B.plane_intersection_count(a, b)) == len(to_set(a_cols) & to_set(b_cols))


def test_not_within_existence(rng):
    exist_cols = rand_cols(rng, 5000)
    a_cols = exist_cols[::3]
    ex = B.bits_to_plane(exist_cols, WORDS)
    a = B.bits_to_plane(a_cols, WORDS)
    got = to_set(B.plane_to_bits(np.asarray(B.plane_not(a, ex))))
    assert got == to_set(exist_cols) - to_set(a_cols)


def test_shift(rng):
    cols = rand_cols(rng, 2000, NBITS - 1)
    plane = B.bits_to_plane(cols, WORDS)
    got = to_set(B.plane_to_bits(np.asarray(B.plane_shift(plane))))
    assert got == {c + 1 for c in to_set(cols)}


def test_shift_drops_last_bit():
    plane = B.bits_to_plane([NBITS - 1, 5], WORDS)
    got = to_set(B.plane_to_bits(np.asarray(B.plane_shift(plane))))
    assert got == {6}


@pytest.mark.parametrize(
    "start,end",
    [(0, 0), (0, 32), (5, 37), (100, 100), (31, 33), (0, NBITS), (1000, 1003)],
)
def test_range_mask(start, end):
    m = np.asarray(B.plane_range_mask(start, end, WORDS))
    assert to_set(B.plane_to_bits(m)) == set(range(start, end))


def test_row_counts(rng):
    rows = [rand_cols(rng, n) for n in (10, 0, 3000, 77)]
    planes = np.stack([B.bits_to_plane(r, WORDS) for r in rows])
    filt_cols = rand_cols(rng, 8000)
    filt = B.bits_to_plane(filt_cols, WORDS)
    got = np.asarray(B.row_counts(planes))
    assert got.tolist() == [len(to_set(r)) for r in rows]
    gotf = np.asarray(B.row_counts(planes, filt))
    assert gotf.tolist() == [len(to_set(r) & to_set(filt_cols)) for r in rows]


def test_full_shard_shapes():
    # Sanity at the real shard width (2^20 columns, reference
    # shardwidth/helper.go:14).
    assert WORDS_PER_SHARD * 32 == SHARD_WIDTH
    plane = B.bits_to_plane([0, SHARD_WIDTH - 1], WORDS_PER_SHARD)
    assert int(B.plane_count(plane)) == 2
