"""SQL parser grammar battery for the JOIN surface (reference:
sql3/parser — this engine recognizes INNER and LEFT joins with a
single-conjunct ON and errors clearly on everything else, it never
silently misparses an unsupported join)."""

import pytest

from pilosa_tpu.sql import ast
from pilosa_tpu.sql.lexer import SQLError
from pilosa_tpu.sql.parser import parse_statement


def _sel(sql):
    s = parse_statement(sql)
    assert isinstance(s, ast.SelectStatement)
    return s


class TestJoinGrammar:
    def test_bare_join_is_inner(self):
        s = _sel("SELECT a FROM f JOIN d ON f.k = d._id")
        assert len(s.joins) == 1
        j = s.joins[0]
        assert (j.table, j.kind) == ("d", "INNER")
        assert isinstance(j.on, ast.Binary) and j.on.op == "="

    def test_inner_keyword(self):
        s = _sel("SELECT a FROM f INNER JOIN d ON f.k = d._id")
        assert s.joins[0].kind == "INNER"

    def test_left_and_left_outer(self):
        for kw in ("LEFT JOIN", "LEFT OUTER JOIN"):
            s = _sel(f"SELECT a FROM f {kw} d ON f.k = d._id")
            assert s.joins[0].kind == "LEFT"

    def test_aliases_as_and_bare(self):
        s = _sel("SELECT x.a FROM fact AS x JOIN dim y ON x.k = y._id")
        assert s.table_alias == "x"
        assert s.joins[0].alias == "y"

    def test_qualified_on_columns(self):
        s = _sel("SELECT f.a FROM fact f JOIN dim d ON f.fk = d._id")
        on = s.joins[0].on
        assert (on.left.table, on.left.name) == ("f", "fk")
        assert (on.right.table, on.right.name) == ("d", "_id")

    def test_reversed_on_order(self):
        # dim._id = fact.fk parses the same shape; direction is the
        # planner's problem, not the parser's
        s = _sel("SELECT f.a FROM fact f JOIN dim d ON d._id = f.fk")
        on = s.joins[0].on
        assert (on.left.table, on.left.name) == ("d", "_id")

    def test_multi_join_chain(self):
        s = _sel(
            "SELECT f.a FROM fact f "
            "JOIN d1 ON f.k1 = d1._id "
            "LEFT JOIN d2 ON f.k2 = d2._id "
            "JOIN d3 x ON f.k3 = x._id")
        assert [(j.table, j.kind) for j in s.joins] == [
            ("d1", "INNER"), ("d2", "LEFT"), ("d3", "INNER")]
        assert s.joins[2].alias == "x"

    def test_join_with_tail_clauses(self):
        s = _sel(
            "SELECT d.y, SUM(f.v) AS r FROM fact f "
            "JOIN dim d ON f.k = d._id WHERE d.z = 3 "
            "GROUP BY d.y HAVING SUM(f.v) > 0 "
            "ORDER BY r DESC LIMIT 5")
        assert len(s.joins) == 1 and s.limit == 5
        assert s.order_by[0].desc

    @pytest.mark.parametrize("kind", ["RIGHT", "FULL", "CROSS"])
    def test_unsupported_kinds_error_clearly(self, kind):
        with pytest.raises(SQLError, match=f"{kind} JOIN is not supported"):
            parse_statement(
                f"SELECT a FROM f {kind} JOIN d ON f.k = d._id")

    def test_unsupported_kind_not_eaten_as_alias(self):
        # before RIGHT/FULL/CROSS were keywords this parsed as table
        # alias "RIGHT" + INNER join — silent wrong semantics
        with pytest.raises(SQLError):
            parse_statement("SELECT a FROM f RIGHT JOIN d ON f.k = d._id")

    def test_soft_keywords_stay_usable_as_columns(self):
        s = _sel("SELECT right, full, cross FROM f WHERE right = 1")
        assert [it.expr.name for it in s.items] == [
            "right", "full", "cross"]

    def test_join_requires_on(self):
        with pytest.raises(SQLError):
            parse_statement("SELECT a FROM f JOIN d WHERE a = 1")
