"""Data-model tests: fragments, fields, index, holder, persistence.

Mirrors the reference's fragment/field/index internal tests
(fragment_internal_test.go, field_test.go, index_test.go) at the
behaviors that matter for query semantics.
"""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.core import (
    EXISTENCE_FIELD,
    Field,
    FieldOptions,
    FieldType,
    Holder,
    Index,
    IndexOptions,
)
from pilosa_tpu.core.fragment import BSIFragment, SetFragment
from pilosa_tpu.ops.bitmap import plane_to_bits
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import load_holder_data, save_holder_data

W = 1 << 9  # small planes for fragment-level tests


def bits(plane):
    return set(int(x) for x in plane_to_bits(np.asarray(plane)))


class TestSetFragment:
    def test_set_clear(self):
        f = SetFragment(0, W)
        assert f.set_bit(3, 100)
        assert not f.set_bit(3, 100)  # already set
        assert f.set_bit(3, 101)
        assert f.set_bit(9, 100)
        assert bits(f.row_plane(3)) == {100, 101}
        assert bits(f.row_plane(9)) == {100}
        assert f.clear_bit(3, 100)
        assert not f.clear_bit(3, 100)
        assert bits(f.row_plane(3)) == {101}
        assert bits(f.row_plane(777)) == set()

    def test_set_many(self):
        f = SetFragment(0, W)
        n = f.set_many([1, 1, 2, 2, 2], [10, 11, 10, 11, 11])
        assert n == 4  # duplicate (2,11) counted once
        assert bits(f.row_plane(1)) == {10, 11}
        assert bits(f.row_plane(2)) == {10, 11}

    def test_clear_column_mutex(self):
        f = SetFragment(0, W)
        for r in range(5):
            f.set_bit(r, 42)
        f.set_bit(2, 43)
        assert f.clear_column(42, except_row=2)
        assert bits(f.row_plane(2)) == {42, 43}
        for r in (0, 1, 3, 4):
            assert bits(f.row_plane(r)) == set()
        assert not f.clear_column(42, except_row=2)  # nothing left to clear

    def test_device_cache_invalidation(self):
        f = SetFragment(0, W)
        f.set_bit(0, 1)
        d1 = f.device_planes()
        assert f.device_planes() is d1  # cached
        f.set_bit(0, 2)
        d2 = f.device_planes()
        assert d2 is not d1
        assert bits(np.asarray(d2)[0]) == {1, 2}

    def test_capacity_growth_pow2(self):
        f = SetFragment(0, W)
        for r in range(20):
            f.set_bit(r * 7, 1)
        assert f.planes.shape[0] == 32  # next pow2 >= 20
        assert f.existing_rows() == [r * 7 for r in range(20)]


class TestBSIFragment:
    def test_set_get_clear(self):
        f = BSIFragment(0, W)
        f.set_value(10, 1234)
        f.set_value(11, -77)
        f.set_value(12, 0)
        assert f.value(10) == 1234
        assert f.value(11) == -77
        assert f.value(12) == 0
        assert f.value(13) is None
        f.set_value(10, -5)  # overwrite shrinks magnitude, must fully clear
        assert f.value(10) == -5
        assert f.clear_value(11)
        assert f.value(11) is None
        assert not f.clear_value(11)

    def test_depth_growth(self):
        f = BSIFragment(0, W)
        f.set_value(1, 3)
        assert f.depth == 2
        f.set_value(2, 1 << 40)
        assert f.depth == 41
        assert f.value(1) == 3
        assert f.value(2) == 1 << 40

    def test_set_values_last_wins(self):
        f = BSIFragment(0, W)
        f.set_values([5, 6, 5], [100, 200, 300])
        assert f.value(5) == 300
        assert f.value(6) == 200


class TestField:
    def test_mutex_semantics(self):
        fld = Field("i", "m", FieldOptions(type=FieldType.MUTEX))
        fld.set_bit(1, 100)
        fld.set_bit(2, 100)  # must clear row 1 for col 100
        frag = fld.fragment(0)
        assert bits(frag.row_plane(1)) == set()
        assert bits(frag.row_plane(2)) == {100}

    def test_bool_semantics(self):
        fld = Field("i", "b", FieldOptions(type=FieldType.BOOL))
        fld.set_bool(7, True)
        fld.set_bool(7, False)
        frag = fld.fragment(0)
        assert bits(frag.row_plane(1)) == set()
        assert bits(frag.row_plane(0)) == {7}

    def test_time_views(self):
        fld = Field("i", "t", FieldOptions(type=FieldType.TIME, time_quantum="YMD"))
        ts = dt.datetime(2010, 1, 2, 3)
        fld.set_bit(1, 5, timestamp=ts)
        assert set(fld.view_names()) == {
            "standard", "standard_2010", "standard_201001", "standard_20100102",
        }
        for v in fld.view_names():
            assert bits(fld.fragment(0, v).row_plane(1)) == {5}

    def test_shard_routing(self):
        fld = Field("i", "s", FieldOptions())
        col = 3 * SHARD_WIDTH + 17
        fld.set_bit(9, col)
        assert fld.shards() == {3}
        assert bits(fld.fragment(3).row_plane(9)) == {17}

    def test_decimal_scale(self):
        fld = Field("i", "d", FieldOptions(type=FieldType.DECIMAL, scale=2))
        fld.set_value(1, 12.34)
        assert fld.value(1) == pytest.approx(12.34)

    def test_timestamp_roundtrip(self):
        fld = Field("i", "ts", FieldOptions(type=FieldType.TIMESTAMP))
        fld.set_value(1, "2020-05-06T07:08:09Z")
        v = fld.value(1)
        assert v == dt.datetime(2020, 5, 6, 7, 8, 9,
                                tzinfo=dt.timezone.utc).timestamp()

    def test_int_min_max_enforced(self):
        fld = Field("i", "n", FieldOptions(type=FieldType.INT, min=0, max=100))
        fld.set_value(1, 50)
        with pytest.raises(ValueError):
            fld.set_value(1, 101)
        with pytest.raises(ValueError):
            fld.set_value(1, -1)


class TestIndexHolder:
    def test_existence_tracking(self):
        idx = Index("i")
        assert EXISTENCE_FIELD in idx.fields
        idx.add_exists(10)
        idx.add_exists(SHARD_WIDTH + 5)
        assert bits(idx.existence_plane(0)) == {10}
        assert bits(idx.existence_plane(1)) == {5}
        assert idx.existence_plane(7) is None

    def test_field_crud(self):
        idx = Index("i")
        idx.create_field("f")
        with pytest.raises(ValueError):
            idx.create_field("f")
        with pytest.raises(ValueError):
            idx.create_field("BadCase")
        assert [f.name for f in idx.public_fields()] == ["f"]
        idx.delete_field("f")
        assert idx.public_fields() == []
        with pytest.raises(ValueError):
            idx.delete_field(EXISTENCE_FIELD)

    def test_invalid_index_name(self):
        for bad in ("", "9lives", "UPPER"):
            with pytest.raises(ValueError):
                Index(bad)

    def test_holder_schema_persistence(self, tmp_path):
        h = Holder(str(tmp_path))
        idx = h.create_index("trips", IndexOptions(keys=False))
        idx.create_field("dist", FieldOptions(type=FieldType.INT))
        idx.create_field("tags", FieldOptions(type=FieldType.SET, keys=True))
        h.save_schema()

        h2 = Holder(str(tmp_path))
        assert set(h2.indexes) == {"trips"}
        assert h2.index("trips").field("dist").options.type == FieldType.INT
        assert h2.index("trips").field("tags").options.keys

    def test_holder_data_roundtrip(self, tmp_path):
        h = Holder(str(tmp_path))
        idx = h.create_index("i")
        f = idx.create_field("f")
        f.set_bit(3, 100)
        f.set_bit(5, SHARD_WIDTH + 1)
        n = idx.create_field("n", FieldOptions(type=FieldType.INT))
        n.set_value(100, -42)
        idx.add_exists(100)
        save_holder_data(h)

        h2 = Holder(str(tmp_path))
        load_holder_data(h2)
        f2 = h2.index("i").field("f")
        assert bits(f2.fragment(0).row_plane(3)) == {100}
        assert bits(f2.fragment(1).row_plane(5)) == {1}
        assert h2.index("i").field("n").value(100) == -42
        assert bits(h2.index("i").existence_plane(0)) == {100}

    def test_translation(self, tmp_path):
        from pilosa_tpu.hashing import key_to_partition, shard_to_partition
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        h = Holder(str(tmp_path))
        idx = h.create_index("i", IndexOptions(keys=True))
        ids = idx.translate.create_keys(["alice", "bob"])
        assert set(ids) == {"alice", "bob"}
        # Record-key IDs land in a shard whose partition matches the
        # key's partition (reference: translate.go:103), and stay stable.
        for k, id_ in ids.items():
            assert id_ >= 1  # 0 stays invalid
            assert (shard_to_partition("i", id_ // SHARD_WIDTH)
                    == key_to_partition("i", k))
        again = idx.translate.create_keys(["bob", "carol"])
        assert again["bob"] == ids["bob"]
        assert len({*ids.values(), again["carol"]}) == 3  # all distinct
        # Row keys start at 1 (0 reserved).
        f = idx.create_field("f", FieldOptions(keys=True))
        rows = f.translate.create_keys(["x"])
        assert rows == {"x": 1}
        # Journal replay.
        h2 = Holder(str(tmp_path))
        idx2 = h2.index("i")
        assert idx2.translate.find_keys(["alice", "carol"]) == {
            "alice": ids["alice"], "carol": again["carol"]}
        assert idx2.translate.translate_ids([ids["bob"]]) == {ids["bob"]: "bob"}
        # Replayed stores keep allocating fresh IDs.
        dave = idx2.translate.create_keys(["dave"])["dave"]
        assert dave not in {ids["alice"], ids["bob"], again["carol"]}


class TestParanoia:
    """Opt-in invariant re-validation (reference: roaringparanoia /
    roaringsentinel build tags, SURVEY §5.2)."""

    def test_paranoia_catches_corruption(self, monkeypatch):
        from pilosa_tpu.core import fragment as fragmod

        monkeypatch.setattr(fragmod, "PARANOIA", True)
        frag = fragmod.SetFragment(0)
        frag.set_bit(1, 5)  # healthy mutation passes
        frag.row_index[99] = 7  # corrupt the slot map
        with pytest.raises(AssertionError):
            frag.set_bit(1, 6)

    def test_paranoia_bsi_exists_invariant(self, monkeypatch):
        import numpy as np

        from pilosa_tpu.core import fragment as fragmod
        from pilosa_tpu.ops import bsi as bsiops

        monkeypatch.setattr(fragmod, "PARANOIA", True)
        frag = fragmod.BSIFragment(0)
        frag.set_values([1, 2], [3, 4])
        # magnitude bit without existence = corruption
        frag.planes[bsiops.OFFSET, 100] = np.uint32(1)
        with pytest.raises(AssertionError):
            frag.set_values([3], [5])

    def test_budget_audit_detects_drift(self):
        from pilosa_tpu.core.stacked import DeviceBudget

        b = DeviceBudget(1 << 20)
        b.charge(("x", 0), 100, lambda: None)
        b.audit()
        b.used += 7  # simulated leak
        with pytest.raises(AssertionError):
            b.audit()
