"""Cluster health plane: timeline sampler, SLO burn rates, trace
exemplars, flight recorder — plus the registry hardening that rode
along (exposition escaping, deque history ring, thread-safety)."""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs import tracing as T
from pilosa_tpu.obs.flight import FlightRecorder
from pilosa_tpu.obs.health import HealthPlane
from pilosa_tpu.obs.history import ExecutionRequestsAPI
from pilosa_tpu.obs.slo import Objective, SLOTracker
from pilosa_tpu.obs.timeline import TimelineSampler, estimate_quantile
from pilosa_tpu.sched.clock import ManualClock


# ---------------------------------------------------------------------------
# satellite: Prometheus exposition escaping
# ---------------------------------------------------------------------------


class TestExpositionEscaping:
    def test_label_values_escaped_per_spec(self):
        reg = M.MetricsRegistry()
        reg.count("q_total", q='say "hi"\nback\\slash')
        lines = [l for l in reg.prometheus_text().splitlines()
                 if l.startswith("pilosa_q_total{")]
        assert lines == [
            'pilosa_q_total{q="say \\"hi\\"\\nback\\\\slash"} 1']
        # the raw value never leaks an unescaped quote or newline into
        # the exposition line
        assert "\n" not in lines[0]

    def test_clean_values_unchanged(self):
        reg = M.MetricsRegistry()
        reg.gauge("g", 2.0, node="n1")
        assert 'pilosa_g{node="n1"} 2.0' in reg.prometheus_text()


# ---------------------------------------------------------------------------
# satellite: history ring is a deque with a serve limit
# ---------------------------------------------------------------------------


class TestHistoryRing:
    def test_deque_eviction_keeps_newest(self):
        h = ExecutionRequestsAPI(capacity=5)
        for i in range(8):
            rec = h.begin("i", f"q{i}", "pql")
            h.end(rec)
        out = h.list()
        assert len(out) == 5
        assert [r.query for r in out] == ["q7", "q6", "q5", "q4", "q3"]

    def test_list_limit(self):
        h = ExecutionRequestsAPI(capacity=10)
        for i in range(6):
            h.end(h.begin("i", f"q{i}", "pql"))
        assert [r.query for r in h.list(limit=2)] == ["q5", "q4"]
        assert h.list(limit=0) == []
        assert len(h.list(limit=99)) == 6


# ---------------------------------------------------------------------------
# satellite: registry thread-safety under reader/writer load
# ---------------------------------------------------------------------------


class TestRegistryThreadSafety:
    def test_hammer_with_concurrent_exposition(self):
        reg = M.MetricsRegistry()
        iters, writers = 500, 8
        errors = []
        stop = threading.Event()

        def writer(tid):
            try:
                for i in range(iters):
                    reg.count("hammer_total", labelled=str(tid % 2))
                    reg.gauge("hammer_gauge", float(i))
                    reg.observe_bucketed(
                        "hammer_ms", float(i % 40), (5.0, 10.0, 20.0))
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(e)

        def reader():
            try:
                while not stop.is_set():
                    reg.prometheus_text()
                    reg.as_json()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(writers)]
        readers = [threading.Thread(target=reader) for _ in range(2)]
        for t in readers + threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        for t in readers:
            t.join()
        assert errors == []
        total = sum(reg.value("hammer_total", labelled=str(v))
                    for v in (0, 1))
        assert total == writers * iters
        h = reg.histogram("hammer_ms")
        assert h["count"] == writers * iters


# ---------------------------------------------------------------------------
# timeline sampler
# ---------------------------------------------------------------------------


class TestTimelineSampler:
    def test_counter_deltas_become_rates(self):
        reg = M.MetricsRegistry()
        clock = ManualClock()
        tl = TimelineSampler(interval_ms=100, capacity=10, registry=reg,
                             clock=clock)
        reg.count("reqs_total", 5)
        first = tl.sample()
        assert first["rates"] == {}  # no previous sample to diff against
        clock.advance(2.0)
        reg.count("reqs_total", 10)
        second = tl.sample()
        assert second["rates"]["reqs_total"] == pytest.approx(5.0)

    def test_histogram_quantiles_over_interval_deltas(self):
        reg = M.MetricsRegistry()
        clock = ManualClock()
        tl = TimelineSampler(registry=reg, clock=clock)
        for v in (3.0, 3.0, 3.0, 3.0):
            reg.observe_bucketed("lat_ms", v, (2.0, 4.0, 8.0))
        s = tl.sample()
        q = s["quantiles"]["lat_ms"]
        assert q["count"] == 4
        assert 2.0 <= q["p50"] <= 4.0
        clock.advance(1.0)
        s2 = tl.sample()  # no new observations -> series omitted
        assert "lat_ms" not in s2["quantiles"]

    def test_estimate_quantile_interpolates(self):
        assert estimate_quantile([10.0, 20.0, 30.0], [0, 4, 0, 0], 0.5) \
            == pytest.approx(15.0)
        # overflow bucket clamps to the last bound
        assert estimate_quantile([10.0, 20.0], [0, 0, 3], 0.99) == 20.0
        assert estimate_quantile([10.0], [0, 0], 0.5) == 0.0

    def test_estimate_quantile_empty_delta_window(self):
        # an interval where no histogram observations landed produces an
        # all-zero delta; any quantile over it must be 0.0, not a crash
        for q in (0.0, 0.5, 0.99, 1.0):
            assert estimate_quantile([10.0, 20.0], [0, 0, 0], q) == 0.0
        assert estimate_quantile([], [], 0.5) == 0.0
        # negative deltas (counter reset mid-window) also sum to <= 0
        assert estimate_quantile([10.0], [-2, 0], 0.5) == 0.0

    def test_estimate_quantile_single_populated_bucket(self):
        bounds = [10.0, 20.0, 30.0]
        # every quantile interpolates within the one live bucket
        assert estimate_quantile(bounds, [0, 10, 0, 0], 0.1) \
            == pytest.approx(11.0)
        assert estimate_quantile(bounds, [0, 10, 0, 0], 1.0) \
            == pytest.approx(20.0)
        # first bucket interpolates from an implicit 0.0 lower edge
        assert estimate_quantile(bounds, [4, 0, 0, 0], 0.5) \
            == pytest.approx(5.0)

    def test_estimate_quantile_all_counts_in_overflow(self):
        # nothing sane can be interpolated past +Inf: clamp to bounds[-1]
        bounds = [10.0, 20.0, 30.0]
        for q in (0.01, 0.5, 1.0):
            assert estimate_quantile(bounds, [0, 0, 0, 7], q) == 30.0
        # degenerate: overflow counts but no finite bounds at all
        assert estimate_quantile([], [5], 0.5) == 0.0

    def test_estimate_quantile_exact_bucket_boundary(self):
        # rank landing exactly on a bucket's cumulative edge stays inside
        # that bucket and interpolates to its upper bound, not past it
        bounds = [10.0, 20.0]
        counts = [2, 2, 0]  # cum edges at rank 2 and 4
        assert estimate_quantile(bounds, counts, 0.5) \
            == pytest.approx(10.0)  # rank=2 == first bucket's cum edge
        assert estimate_quantile(bounds, counts, 1.0) \
            == pytest.approx(20.0)
        # q=0 takes the first populated bucket's lower edge
        assert estimate_quantile(bounds, counts, 0.0) \
            == pytest.approx(0.0)

    def test_window_filters_by_clock(self):
        clock = ManualClock()
        tl = TimelineSampler(registry=M.MetricsRegistry(), clock=clock)
        for _ in range(3):
            tl.sample()
            clock.advance(2.0)
        # now=6; samples at t=0,2,4
        assert len(tl.window(2.5)) == 1
        assert len(tl.window(5.0)) == 2
        assert len(tl.window(None)) == 3

    def test_sick_probe_degrades_not_fatal(self):
        tl = TimelineSampler(registry=M.MetricsRegistry(),
                             clock=ManualClock())
        tl.add_probe("bad", lambda: 1 / 0)
        tl.add_probe("good", lambda: {"v": 1})
        s = tl.sample()
        assert "error" in s["probes"]["bad"]
        assert s["probes"]["good"] == {"v": 1}

    def test_maybe_sample_respects_cadence(self):
        clock = ManualClock()
        tl = TimelineSampler(interval_ms=1000, registry=M.MetricsRegistry(),
                             clock=clock)
        assert tl.maybe_sample() is not None  # first call always samples
        assert tl.maybe_sample() is None      # same instant: not due
        clock.advance(1.5)
        assert tl.maybe_sample() is not None

    def test_ring_bounded(self):
        clock = ManualClock()
        tl = TimelineSampler(capacity=4, registry=M.MetricsRegistry(),
                             clock=clock)
        for _ in range(9):
            tl.sample()
            clock.advance(1.0)
        assert len(tl) == 4


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------


def _latency_slo(threshold_ms=100.0, target=0.9):
    return Objective("q-lat", "query", "latency", target,
                     threshold_ms=threshold_ms)


class TestSLOTracker:
    def test_burn_rate_is_bad_fraction_over_budget(self):
        clock = ManualClock()
        slo = SLOTracker(objectives=[_latency_slo()], registry=M.MetricsRegistry(),
                         clock=clock, fast_burn_alert=4.0)
        for i in range(10):
            slo.record("query", 500.0 if i < 5 else 10.0)
        row = slo.burn_rates()[0]
        # 5/10 bad over a 10% budget -> burning 5x
        assert row["fast_burn"] == pytest.approx(5.0)
        assert row["alerting"] is True
        assert slo.status()["alerting"] == ["q-lat"]

    def test_min_events_guards_single_sample_spikes(self):
        slo = SLOTracker(objectives=[_latency_slo()],
                         registry=M.MetricsRegistry(), clock=ManualClock(),
                         fast_burn_alert=1.0, min_events=5)
        slo.record("query", 9999.0)
        row = slo.burn_rates()[0]
        assert row["fast_burn"] > 1.0 and row["alerting"] is False

    def test_error_objective(self):
        obj = Objective("q-err", "query", "errors", 0.99)
        slo = SLOTracker(objectives=[obj], registry=M.MetricsRegistry(),
                         clock=ManualClock())
        for i in range(10):
            slo.record("query", 1.0, error=(i == 0))
        row = slo.burn_rates()[0]
        assert row["fast_burn"] == pytest.approx(10.0)  # 10% errors / 1%

    def test_events_age_out_of_fast_window(self):
        clock = ManualClock()
        slo = SLOTracker(objectives=[_latency_slo()],
                         registry=M.MetricsRegistry(), clock=clock,
                         fast_window_s=60.0, slow_window_s=600.0)
        for _ in range(6):
            slo.record("query", 500.0)
        assert slo.burn_rates()[0]["fast_burn"] > 0
        clock.advance(120.0)
        row = slo.burn_rates()[0]
        assert row["fast_burn"] == 0.0          # aged out of fast window
        assert row["slow_burn"] > 0.0           # still in the slow window

    def test_publishes_gauges(self):
        reg = M.MetricsRegistry()
        slo = SLOTracker(objectives=[_latency_slo()], registry=reg,
                         clock=ManualClock())
        slo.record("query", 500.0)
        slo.burn_rates()
        assert reg.value(M.METRIC_SLO_BURN_RATE, slo="q-lat",
                         window="fast") > 0


# ---------------------------------------------------------------------------
# trace exemplars
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_bucket_links_to_active_trace(self):
        prev = T.get_tracer()
        tracer = T.set_tracer(T.Tracer(enabled=True, sample_rate=1.0,
                                       store=T.TraceStore(8)))
        reg = M.MetricsRegistry(exemplars=True)
        try:
            span = tracer.start_trace("x")
            reg.observe_bucketed("lat_ms", 3.0, (1.0, 5.0, 10.0))
            span.finish()
        finally:
            T.set_tracer(prev)
        text = reg.prometheus_text()
        line = next(l for l in text.splitlines()
                    if l.startswith('pilosa_lat_ms_bucket{le="5"'))
        assert f'# {{trace_id="{span.trace_id}"}} 3' in line

    def test_disabled_by_default(self):
        prev = T.get_tracer()
        tracer = T.set_tracer(T.Tracer(enabled=True, sample_rate=1.0))
        reg = M.MetricsRegistry()  # exemplars off
        try:
            span = tracer.start_trace("x")
            reg.observe_bucketed("lat_ms", 3.0, (1.0, 5.0))
            span.finish()
        finally:
            T.set_tracer(prev)
        assert "trace_id=" not in reg.prometheus_text()

    def test_no_exemplar_outside_trace(self):
        reg = M.MetricsRegistry(exemplars=True)
        reg.observe_bucketed("lat_ms", 3.0, (1.0, 5.0))
        assert "trace_id=" not in reg.prometheus_text()

    def test_trace_histograms_carry_exemplars_at_finish(self):
        # the tracer observes trace_duration_ms/_stage_latency_ms AFTER
        # the span scope is reset, so the trace ID rides explicitly
        prev = T.get_tracer()
        reg = M.MetricsRegistry(exemplars=True)
        tracer = T.set_tracer(T.Tracer(enabled=True, sample_rate=1.0,
                                       registry=reg))
        try:
            span = tracer.start_trace("q")
            with tracer.start_span("stage.one"):
                pass
            span.finish()
        finally:
            T.set_tracer(prev)
        text = reg.prometheus_text()
        for series in ("trace_duration_ms_bucket",
                       "trace_stage_latency_ms_bucket"):
            line = next(l for l in text.splitlines()
                        if l.startswith(f"pilosa_{series}")
                        and "trace_id=" in l)
            assert f'trace_id="{span.trace_id}"' in line

    def test_disable_health_clears_exemplar_flag(self):
        from pilosa_tpu.api import API
        from pilosa_tpu.config import Config

        api = API()
        assert M.REGISTRY.exemplars is False
        api.enable_health(config=Config(obs_timeline_exemplars=True))
        assert M.REGISTRY.exemplars is True
        api.disable_health()
        assert M.REGISTRY.exemplars is False


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def _plane(clock, reg, **kw):
    kw.setdefault("interval_ms", 100.0)
    kw.setdefault("min_events", 1)
    return HealthPlane(registry=reg, clock=clock, **kw)


class TestFlightRecorder:
    def test_wal_stall_trigger(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, wal_stall_s=5.0)
        hp.timeline.add_probe("wal", lambda: {"flush_lag_s": 9.0})
        hp.timeline.sample()
        bundles = hp.flight.bundles()
        assert [b["trigger"] for b in bundles] == ["wal_stall"]
        assert "9.0s" in bundles[0]["reason"]

    def test_breaker_open_trigger_from_probe(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg)
        hp.timeline.add_probe(
            "breakers",
            lambda: {"enabled": True, "states": {"n2": "open",
                                                 "n3": "closed"}})
        hp.timeline.sample()
        b = hp.flight.bundles()[0]
        assert b["trigger"] == "breaker_open" and "n2" in b["reason"]

    def test_eviction_storm_trigger(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, eviction_rate=10.0)
        hp.timeline.sample()
        clock.advance(1.0)
        reg.count(M.METRIC_DEVICE_STACK_EVICTIONS, 50)
        hp.timeline.sample()
        assert [b["trigger"] for b in hp.flight.bundles()] \
            == ["eviction_storm"]

    def test_slow_query_burst_trigger(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, slow_burst_per_s=5.0)
        hp.timeline.sample()
        clock.advance(1.0)
        reg.count(M.METRIC_TRACE_SLOW_QUERIES, 10, kind="pql")
        hp.timeline.sample()
        assert [b["trigger"] for b in hp.flight.bundles()] \
            == ["slow_query_burst"]

    def test_membership_flap_trigger(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, membership_flap_transitions=6.0)
        flaps = {"n": 2}
        hp.timeline.add_probe(
            "membership",
            lambda: {"enabled": True, "alive": 3, "suspect": 0, "down": 0,
                     "recent_transitions": flaps["n"]})
        hp.timeline.sample()
        assert hp.flight.bundles() == []  # 2 transitions: normal churn
        clock.advance(1.0)
        flaps["n"] = 7
        hp.timeline.sample()
        bundles = hp.flight.bundles()
        assert [b["trigger"] for b in bundles] == ["membership_flap"]
        assert "7 membership transitions" in bundles[0]["reason"]

    def test_membership_probe_absent_never_fires(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, membership_flap_transitions=1.0)
        hp.timeline.sample()  # no membership probe attached at all
        assert hp.flight.bundles() == []

    def test_cooldown_bounds_refires(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, wal_stall_s=1.0, flight_cooldown_s=30.0)
        hp.timeline.add_probe("wal", lambda: {"flush_lag_s": 5.0})
        hp.timeline.sample()
        clock.advance(5.0)
        hp.timeline.sample()  # still stalled, but inside the cooldown
        assert len(hp.flight.bundles()) == 1
        clock.advance(31.0)
        hp.timeline.sample()
        assert len(hp.flight.bundles()) == 2

    def test_bundle_contents_and_lookup(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, wal_stall_s=1.0)
        hp.flight.record_event("note", detail="before")
        hp.timeline.add_probe("wal", lambda: {"flush_lag_s": 5.0})
        hp.timeline.sample()
        b = hp.flight.bundles()[0]
        assert b["events"][0]["kind"] == "note"
        assert len(b["timeline"]) >= 1
        assert "objectives" in b["slo"]
        assert hp.flight.get(b["id"])["id"] == b["id"]
        with pytest.raises(KeyError):
            hp.flight.get("fb-nope")

    def test_disk_dump(self, tmp_path):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, wal_stall_s=1.0,
                    dump_dir=str(tmp_path / "dumps"))
        hp.timeline.add_probe("wal", lambda: {"flush_lag_s": 5.0})
        hp.timeline.sample()
        b = hp.flight.bundles()[0]
        path = tmp_path / "dumps" / f"{b['id']}.json"
        assert path.exists()
        assert json.loads(path.read_text())["trigger"] == "wal_stall"

    def test_counts_bundles_metric(self):
        clock, reg = ManualClock(), M.MetricsRegistry()
        hp = _plane(clock, reg, wal_stall_s=1.0)
        hp.timeline.add_probe("wal", lambda: {"flush_lag_s": 5.0})
        hp.timeline.sample()
        assert reg.value(M.METRIC_FLIGHT_BUNDLES,
                         trigger="wal_stall") == 1


# ---------------------------------------------------------------------------
# API integration + env bootstrap
# ---------------------------------------------------------------------------


class TestAPIHealth:
    def test_query_paths_feed_slo(self):
        from pilosa_tpu.api import API

        api = API()
        clock = ManualClock()
        hp = api.enable_health(clock=clock, interval_ms=100.0)
        try:
            api.create_index("i")
            api.create_field("i", "f")
            api.import_bits("i", "f", rows=[0], cols=[0])
            clock.advance(1.0)
            api.query("i", "Count(Row(f=0))")
            rows = {r["name"]: r for r in hp.slo.burn_rates()}
            assert rows["query-latency"]["events_fast"] == 1
            assert rows["ingest-latency"]["events_fast"] == 1
            assert hp.timeline.latest() is not None
        finally:
            api.disable_health()
        assert api.health is None

    def test_env_bootstrap_zero_threads(self, monkeypatch):
        from pilosa_tpu.api import API

        monkeypatch.setenv("PILOSA_TPU_OBS_TIMELINE", "1")
        before = threading.active_count()
        api = API()
        try:
            assert api.health is not None
            assert api.health.timeline.running is False
            assert threading.active_count() == before
            api.create_index("i")
            api.create_field("i", "f")
            api.query("i", "Count(Row(f=0))")
        finally:
            api.disable_health()

    def test_from_config(self):
        from pilosa_tpu.config import Config

        cfg = Config(obs_timeline_interval_ms=50.0,
                     obs_timeline_capacity=7,
                     obs_timeline_slo_fast_burn_alert=2.5)
        hp = HealthPlane.from_config(cfg, registry=M.MetricsRegistry())
        assert hp.timeline.interval_s == pytest.approx(0.05)
        assert hp.timeline._ring.maxlen == 7
        assert hp.slo.fast_burn_alert == 2.5


# ---------------------------------------------------------------------------
# the acceptance scenario: 3-node cluster, slow node, burn -> bundle
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url) as r:
        return json.load(r)


class TestClusterHealthAcceptance:
    def test_slow_node_burn_fires_flight_recorder(self):
        from pilosa_tpu.cluster import LocalCluster
        from pilosa_tpu.cluster.resilience import FaultPlan
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        prev = T.get_tracer()
        T.set_tracer(T.Tracer(enabled=True, sample_rate=1.0, slow_ms=20.0,
                              store=T.TraceStore(128)))
        plan = FaultPlan(seed=7)
        clock = ManualClock()
        objectives = [
            Objective("query-latency", "query", "latency", 0.99,
                      threshold_ms=10.0),
            Objective("query-errors", "query", "errors", 0.999),
        ]
        try:
            with LocalCluster(3, replica_n=1, fault_plan=plan) as lc:
                coord = lc.coordinator
                coord.enable_resilience(breaker_threshold=1,
                                        breaker_open_ms=60000.0,
                                        hedge=False)
                planes = lc.enable_health(
                    clock=clock, interval_ms=100.0, objectives=objectives,
                    slo_fast_window_s=60.0, slo_slow_window_s=600.0,
                    fast_burn_alert=10.0, min_events=5,
                    flight_cooldown_s=0.5)
                coord.create_index("health")
                coord.create_field("health", "f")
                for s in range(8):
                    coord.import_bits("health", "f", rows=[1],
                                      cols=[s * SHARD_WIDTH + 1])
                peers = [n for n in lc.nodes if n is not coord]
                snap = coord.snapshot()
                owners = {snap.primary_shard_node("health", s).id
                          for s in range(8)}
                assert any(p.node.id in owners for p in peers), \
                    "fixture regression: every shard landed on the coord"

                # phase 1 — injected slow peers: every fan-out query
                # pays >=50ms, blowing the 10ms latency objective
                for p in peers:
                    plan.delay(p.node.id, 0.05, op="query")
                for _ in range(8):
                    clock.advance(0.2)
                    coord.query("health", "Count(Row(f=1))")

                hp = coord.health
                assert hp.slo.status()["alerting"] == ["query-latency"]
                burn_bundles = [b for b in hp.flight.bundles()
                                if b["trigger"] == "slo_fast_burn"]
                assert burn_bundles, "fast burn never fired the recorder"

                # the cluster merge sees all three nodes (op="stats"
                # legs are NOT delayed — the rules scope to op="query")
                for plane in planes[1:]:
                    plane.timeline.sample()
                stats = coord.cluster_stats(window_s=600.0)
                ids = {n.id for n in coord.snapshot().nodes}
                assert set(stats["nodes"]) == ids and len(ids) == 3
                assert all(tl.get("enabled") for tl in
                           stats["nodes"].values())
                assert stats["cluster"]["nodes_reporting"] == 3

                # ... and over real HTTP on the coordinator
                base = coord.node.uri
                http_stats = _get_json(
                    base + "/internal/stats/cluster?window=600")
                assert set(http_stats["nodes"]) == ids
                http_slo = _get_json(base + "/internal/slo")
                assert http_slo["alerting"] == ["query-latency"]
                tl = _get_json(
                    base + "/internal/stats/timeline?window=600")
                assert tl["enabled"] and len(tl["samples"]) >= 1
                # cluster-path queries bypass api.history; seed two
                # records directly to exercise the ?n= serve limit
                for q in ("Count(Row(f=1))", "Count(Row(f=2))"):
                    coord.api.history.end(
                        coord.api.history.begin("health", q, "pql"))
                hist = _get_json(base + "/query-history?n=1")
                assert len(hist) == 1
                assert hist[0]["query"] == "Count(Row(f=2))"

                # phase 2 — drop a shard-owning peer: breaker opens,
                # the transition lands in the event ring, the next
                # sample captures a breaker_open bundle
                victim = next(p for p in peers if p.node.id in owners)
                clock.advance(1.0)
                plan.clear(victim.node.id)
                plan.drop(victim.node.id,
                          first=plan.seen(victim.node.id), op="query")
                with pytest.raises(Exception):
                    coord.query("health", "Count(Row(f=1))")
                assert coord.resilience.breaker.state(
                    victim.node.id) == "open"
                breaker_bundles = [b for b in hp.flight.bundles()
                                   if b["trigger"] == "breaker_open"]
                assert breaker_bundles, "breaker open never captured"
                bundle = breaker_bundles[-1]

                # bundle completeness: timeline window, the breaker
                # transition, and >=1 slow trace that resolves over
                # /internal/traces/{id}
                assert len(bundle["timeline"]) >= 1
                transitions = [e for e in bundle["events"]
                               if e["kind"] == "breaker"
                               and e["to"] == "open"
                               and e["node"] == victim.node.id]
                assert transitions
                assert len(bundle["slow_traces"]) >= 1
                tid = bundle["slow_traces"][0]["traceID"]
                trace = _get_json(base + f"/internal/traces/{tid}")
                assert trace["traceID"] == tid

                # the bundle itself serves over HTTP
                listing = _get_json(base + "/internal/debug/bundles")
                assert bundle["id"] in [b["id"] for b in
                                        listing["bundles"]]
                served = _get_json(
                    base + f"/internal/debug/bundles/{bundle['id']}")
                assert served["trigger"] == "breaker_open"
                with pytest.raises(urllib.error.HTTPError):
                    _get_json(base + "/internal/debug/bundles/fb-nope")
        finally:
            T.set_tracer(prev)
            M.REGISTRY.reset()
