"""HTTP API tests over a live in-process server (reference pattern:
test/cluster.go boots real servers; handler_test.go / http_handler tests).
"""

import json
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.server import serve


@pytest.fixture
def server():
    api = API()
    srv, thread = serve(api, port=0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    yield base
    srv.shutdown()


def req(base, method, path, body=None, ctype="application/json"):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(base + path, data=data, method=method,
                               headers={"Content-Type": ctype})
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read())


def test_full_flow(server):
    base = server
    assert req(base, "POST", "/index/trips")[0] == 200
    assert req(base, "POST", "/index/trips/field/kind")[0] == 200
    assert req(base, "POST", "/index/trips/field/dist",
               {"options": {"type": "int"}})[0] == 200

    # raw-PQL body
    status, out = req(base, "POST", "/index/trips/query",
                      b"Set(1, kind=2)Set(2, kind=2)", ctype="text/plain")
    assert status == 200 and out == {"results": [True, True]}
    # JSON body
    status, out = req(base, "POST", "/index/trips/query",
                      {"query": "Count(Row(kind=2))"})
    assert out == {"results": [2]}

    # bulk imports
    status, out = req(base, "POST", "/index/trips/import",
                      {"field": "kind", "rows": [5, 5], "cols": [10, 11]})
    assert out == {"changed": 2}
    status, out = req(base, "POST", "/index/trips/import-values",
                      {"field": "dist", "cols": [1, 2], "values": [100, -3]})
    assert out == {"imported": 2}
    status, out = req(base, "POST", "/index/trips/query",
                      {"query": "Sum(field=dist)"})
    assert out["results"][0] == {"value": 97, "count": 2}

    # schema & status
    status, out = req(base, "GET", "/schema")
    names = {f["name"] for f in out["indexes"][0]["fields"]}
    assert names == {"kind", "dist"}
    status, out = req(base, "GET", "/status")
    assert out["state"] == "NORMAL"

    # deletes
    assert req(base, "DELETE", "/index/trips/field/dist")[0] == 200
    assert req(base, "DELETE", "/index/trips")[0] == 200
    status, out = req(base, "GET", "/schema")
    assert out == {"indexes": []}


def test_keyed_flow(server):
    base = server
    req(base, "POST", "/index/users", {"options": {"keys": True}})
    req(base, "POST", "/index/users/field/likes", {"options": {"keys": True}})
    req(base, "POST", "/index/users/query",
        b'Set("alice", likes="pizza")Set("bob", likes="pizza")',
        ctype="text/plain")
    _, out = req(base, "POST", "/index/users/query",
                 {"query": 'Row(likes="pizza")'})
    assert out == {"results": [{"keys": ["alice", "bob"]}]}
    _, out = req(base, "POST", "/index/users/import",
                 {"field": "likes", "rowKeys": ["sushi"], "colKeys": ["carol"]})
    assert out == {"changed": 1}
    _, out = req(base, "POST", "/index/users/query",
                 {"query": "TopN(likes)"})
    assert out["results"][0]["rows"][0] == {"key": "pizza", "count": 2}


def test_import_roaring(server):
    import base64

    import numpy as np

    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.roaring import encode_positions

    base = server
    req(base, "POST", "/index/ev")
    req(base, "POST", "/index/ev/field/f")
    # row 3 cols {1, 2}, row 5 col {9} in shard 1 (fragment addressing:
    # row*ShardWidth + col)
    pos = np.array([3 * SHARD_WIDTH + 1, 3 * SHARD_WIDTH + 2,
                    5 * SHARD_WIDTH + 9], dtype=np.uint64)
    blob = base64.b64encode(encode_positions(pos)).decode()
    status, out = req(base, "POST", "/index/ev/shard/1/import-roaring",
                      {"field": "f", "views": {"standard": blob}})
    assert out == {"success": True}
    _, out = req(base, "POST", "/index/ev/query", {"query": "Row(f=3)"})
    assert out["results"][0]["columns"] == [SHARD_WIDTH + 1, SHARD_WIDTH + 2]
    _, out = req(base, "POST", "/index/ev/query", {"query": "Count(All())"})
    assert out["results"][0] == 3
    # clear=true removes bits
    clear_pos = np.array([3 * SHARD_WIDTH + 1], dtype=np.uint64)
    blob = base64.b64encode(encode_positions(clear_pos)).decode()
    req(base, "POST", "/index/ev/shard/1/import-roaring",
        {"field": "f", "views": {"standard": blob}, "clear": True})
    _, out = req(base, "POST", "/index/ev/query", {"query": "Row(f=3)"})
    assert out["results"][0]["columns"] == [SHARD_WIDTH + 2]


def test_import_guards(server):
    base = server
    req(base, "POST", "/index/g")
    req(base, "POST", "/index/g/field/m", {"options": {"type": "mutex"}})
    req(base, "POST", "/index/g/field/n", {"options": {"type": "int"}})
    # mutex exclusivity holds through the bulk path
    req(base, "POST", "/index/g/import",
        {"field": "m", "rows": [3], "cols": [10]})
    req(base, "POST", "/index/g/import",
        {"field": "m", "rows": [5], "cols": [10]})
    _, out = req(base, "POST", "/index/g/query", {"query": "Row(m=3)"})
    assert out["results"][0]["columns"] == []
    _, out = req(base, "POST", "/index/g/query", {"query": "Row(m=5)"})
    assert out["results"][0]["columns"] == [10]
    # set-style imports into BSI fields rejected, not blackholed
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/g/import",
            {"field": "n", "rows": [0], "cols": [1]})
    assert e.value.code == 400
    # value/col length mismatch rejected
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/g/import-values",
            {"field": "n", "cols": [1, 2, 3], "values": [100]})
    assert e.value.code == 400
    # missing required body key is a 400, not 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/g/import", {})
    assert e.value.code == 400
    # clear of a never-set row via roaring doesn't 500 or allocate
    import base64

    import numpy as np

    from pilosa_tpu.storage.roaring import encode_positions
    blob = base64.b64encode(encode_positions(
        np.array([999 * (1 << 20) + 5], dtype=np.uint64))).decode()
    req(base, "POST", "/index/g/field/s")
    status, out = req(base, "POST", "/index/g/shard/0/import-roaring",
                      {"field": "s", "views": {"standard": blob}, "clear": True})
    assert out == {"success": True}
    # truncated roaring blob is a 400 (RoaringError), not a 500
    import struct
    bad = base64.b64encode(
        struct.pack("<II", 12348, 1) + struct.pack("<QHH", 0, 3, 10)
        + struct.pack("<I", 24) + b"\xff\xff").decode()
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/g/shard/0/import-roaring",
            {"field": "s", "views": {"standard": bad}})
    assert e.value.code == 400


def test_errors(server):
    base = server
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/nope/query", {"query": "Count(All())"})
    assert e.value.code == 404
    req(base, "POST", "/index/i")
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/i/query", {"query": "Row(f="})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "GET", "/not-a-route")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req(base, "POST", "/index/i/query", b"\xff\xfe not json",
            ctype="application/json")
    assert e.value.code in (400, 500)


def test_sql_endpoint(server):
    base = server

    def sql(q):
        return req(base, "POST", "/sql", body=q.encode(), ctype="text/plain")

    code, out = sql("CREATE TABLE metros (_id ID, name STRING, pop INT)")
    assert code == 200, out
    code, out = sql("INSERT INTO metros (_id, name, pop) VALUES "
                    "(1, 'nyc', 8000000), (2, 'sf', 800000)")
    assert code == 200 and out["rows-affected"] == 2
    code, out = sql("SELECT _id, name, pop FROM metros WHERE pop > 1000000")
    assert code == 200
    assert out["data"] == [[1, "nyc", 8000000]]
    assert [f["name"] for f in out["schema"]["fields"]] == ["_id", "name", "pop"]
    try:
        code, _ = sql("SELEC nonsense")
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 400


class TestSurfaceCompletion:
    """VERDICT r3 #10: shard-snapshot endpoint, /internal/idalloc/*,
    pprof + per-query profiling."""

    @pytest.fixture()
    def srv(self):
        from pilosa_tpu.api import API
        from pilosa_tpu.server.http import serve

        api = API()
        api.create_index("t")
        api.create_field("t", "f", {"type": "set"})
        api.create_field("t", "n", {"type": "int"})
        api.query("t", "Set(1, f=2)Set(3, f=2)")
        api.import_values("t", "n", cols=[1, 3], values=[7, -4])
        s, _ = serve(api, port=0, background=True)
        yield f"http://{s.server_address[0]}:{s.server_address[1]}", api
        s.shutdown()
        s.server_close()

    def test_shard_snapshot_round_trip(self, srv):
        import io
        import urllib.request

        import numpy as np

        from pilosa_tpu.api import API
        from pilosa_tpu.storage.store import install_shard_arrays

        base, api = srv
        with urllib.request.urlopen(
                base + "/internal/index/t/shard/0/snapshot") as r:
            raw = r.read()
        with np.load(io.BytesIO(raw)) as z:
            arrays = {k: z[k] for k in z.files}
        fresh = API()
        fresh.create_index("t")
        fresh.create_field("t", "f", {"type": "set"})
        fresh.create_field("t", "n", {"type": "int"})
        install_shard_arrays(fresh.holder.index("t"), 0, arrays)
        assert fresh.query("t", "Row(f=2)")[0].columns == [1, 3]
        assert fresh.query("t", "Sum(field=n)")[0].val == 3

    def test_idalloc_over_http(self, srv):
        import json
        import urllib.request

        base, _ = srv

        def post(path, body):
            req = urllib.request.Request(base + path,
                                         data=json.dumps(body).encode(),
                                         method="POST")
            req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        out = post("/internal/idalloc/reserve",
                   {"session": "s1", "count": 10})
        assert out["count"] == 10
        # replay of the same (session, offset) returns the same range
        out2 = post("/internal/idalloc/reserve",
                    {"session": "s1", "count": 10})
        assert out2["base"] == out["base"]
        post("/internal/idalloc/commit", {"session": "s1", "count": 4})
        out3 = post("/internal/idalloc/reserve",
                    {"session": "s2", "count": 5})
        assert out3["base"] == out["base"] + 4  # unused tail returned

    def test_pprof_and_query_profile(self, srv):
        import json
        import urllib.request

        base, _ = srv
        with urllib.request.urlopen(base + "/debug/pprof") as r:
            stacks = json.loads(r.read())["threads"]
        assert stacks and any("http" in "".join(v).lower()
                              for v in stacks.values())
        req = urllib.request.Request(
            base + "/index/t/query?profile=true",
            data=b"Count(Row(f=2))", method="POST")
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["results"] == [2]
        # profile=true returns the query's span tree (latency
        # attribution), not a CPU profile (that's /cpu-profile/start|stop)
        prof = out["profile"]
        assert prof["name"] == "query.profile"
        assert prof["duration_ns"] > 0
        names = {c["name"] for c in prof["children"]}
        assert "query.pql" in names


class TestRouteSurfaceTail:
    """Round-5 HTTP surface additions (reference: http_handler.go routes
    /version /health /schema/details /internal/nodes /internal/shards/max
    /internal/index/{i}/shards /ui/shard-distribution /queries
    /recalculate-caches /cpu-profile/*)."""

    @pytest.fixture(scope="class")
    def base(self):
        api = API()
        api.create_index("rt")
        api.create_field("rt", "f")
        api.query("rt", "Set(1, f=2)Set(1048577, f=3)")
        srv, _ = serve(api, port=0, background=True)
        host, port = srv.server_address[:2]
        yield f"http://{host}:{port}"
        srv.shutdown()
        srv.server_close()

    def _get(self, url):
        import json as _json
        import urllib.request
        with urllib.request.urlopen(url) as r:
            return _json.loads(r.read())

    def _post(self, url, body=b"{}"):
        import json as _json
        import urllib.request
        req = urllib.request.Request(url, data=body, method="POST")
        with urllib.request.urlopen(req) as r:
            return _json.loads(r.read())

    def test_version_health(self, base):
        assert self._get(base + "/version")["version"]
        assert self._get(base + "/health")["state"] == "healthy"

    def test_schema_details_cardinality(self, base):
        det = self._get(base + "/schema/details")
        fld = det["indexes"][0]["fields"][0]
        assert fld["name"] == "f" and fld["cardinality"] == 2

    def test_shards_surfaces(self, base):
        assert self._get(base + "/internal/shards/max")["standard"]["rt"] == 1
        assert self._get(base + "/internal/index/rt/shards")["shards"] == [0, 1]
        dist = self._get(base + "/ui/shard-distribution")
        assert dist["rt"]["local"] == [0, 1]
        nodes = self._get(base + "/internal/nodes")
        assert nodes and nodes[0]["id"]

    def test_queries_and_caches(self, base):
        assert self._get(base + "/queries")["queries"] == []
        assert self._post(base + "/recalculate-caches") == {}

    def test_cpu_profile_roundtrip(self, base):
        self._post(base + "/cpu-profile/start")
        self._get(base + "/schema")
        out = self._post(base + "/cpu-profile/stop")
        assert any("cumulative" in line for line in out["profile"])

    def test_translate_keys_like(self):
        api = API()
        api.create_index("lk", {"keys": True})
        api.create_field("lk", "tag", {"keys": True})
        api.import_bits("lk", "tag", row_keys=["alpha", "beta", "alto"],
                        col_keys=["a", "b", "c"])
        srv, _ = serve(api, port=0, background=True)
        host, port = srv.server_address[:2]
        try:
            out = self._post(f"http://{host}:{port}"
                             "/internal/translate/field/lk/tag/keys/like",
                             b'{"like": "al%"}')
            assert sorted(out["ids"]) == ["alpha", "alto"]
        finally:
            srv.shutdown()
            srv.server_close()
