"""SQL conformance tests, table-driven like the reference's sql3/test/defs
suite (sql3/sql_test.go + sql3/test/defs/defs.go TableTest shapes)."""

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.sql import SQLEngine
from pilosa_tpu.sql.lexer import SQLError


@pytest.fixture()
def eng():
    api = API()
    e = SQLEngine(api)
    e.query("""CREATE TABLE orders (
        _id ID,
        region STRING,
        segment STRING,
        amount INT MIN 0 MAX 100000,
        price DECIMAL(2),
        vip BOOL,
        tags STRINGSET
    )""")
    rows = [
        (1, "east", "retail", 100, 1.5, True, ["red", "blue"]),
        (2, "west", "retail", 200, 2.5, False, ["red"]),
        (3, "east", "wholesale", 300, 3.5, True, ["green"]),
        (4, "north", "retail", 400, 4.5, False, None),
        (5, "east", "retail", 500, 5.5, True, ["blue", "green"]),
    ]
    for (i, r, s, a, p, v, t) in rows:
        tags = "NULL" if t is None else "[" + ",".join(f"'{x}'" for x in t) + "]"
        e.query(f"INSERT INTO orders (_id, region, segment, amount, price, "
                f"vip, tags) VALUES ({i}, '{r}', '{s}', {a}, {p}, "
                f"{'true' if v else 'false'}, {tags})")
    return e


def q(eng, sql):
    return eng.query(sql).data


def test_select_star_count(eng):
    assert q(eng, "SELECT COUNT(*) FROM orders") == [[5]]


def test_select_where_string(eng):
    got = q(eng, "SELECT _id FROM orders WHERE region = 'east'")
    assert got == [[1], [3], [5]]


def test_select_where_and_or(eng):
    got = q(eng, "SELECT _id FROM orders WHERE region = 'east' AND amount > 100")
    assert got == [[3], [5]]
    got = q(eng, "SELECT _id FROM orders WHERE region = 'west' OR vip = true")
    assert got == [[1], [2], [3], [5]]


def test_select_where_not_in_between(eng):
    assert q(eng, "SELECT _id FROM orders WHERE NOT region = 'east'") == [[2], [4]]
    assert q(eng, "SELECT _id FROM orders WHERE region IN ('west','north')") \
        == [[2], [4]]
    assert q(eng, "SELECT _id FROM orders WHERE amount BETWEEN 200 AND 400") \
        == [[2], [3], [4]]


def test_select_columns_values(eng):
    got = q(eng, "SELECT _id, region, amount, vip FROM orders WHERE _id = 2")
    assert got == [[2, "west", 200, False]]


def test_select_decimal_roundtrip(eng):
    got = q(eng, "SELECT price FROM orders WHERE _id = 3")
    assert got == [[3.5]]


def test_select_stringset(eng):
    got = q(eng, "SELECT _id, tags FROM orders WHERE _id = 1")
    assert got[0][0] == 1
    assert sorted(got[0][1]) == ["blue", "red"]


def test_setcontains(eng):
    got = q(eng, "SELECT _id FROM orders WHERE SETCONTAINS(tags, 'red')")
    assert got == [[1], [2]]
    got = q(eng, "SELECT _id FROM orders WHERE SETCONTAINSANY(tags, ['red','green'])")
    assert got == [[1], [2], [3], [5]]
    got = q(eng, "SELECT _id FROM orders WHERE SETCONTAINSALL(tags, ['blue','green'])")
    assert got == [[5]]


def test_is_null(eng):
    assert q(eng, "SELECT _id FROM orders WHERE tags IS NULL") == [[4]]
    assert q(eng, "SELECT _id FROM orders WHERE tags IS NOT NULL") \
        == [[1], [2], [3], [5]]


def test_aggregates(eng):
    assert q(eng, "SELECT SUM(amount) FROM orders") == [[1500]]
    assert q(eng, "SELECT MIN(amount), MAX(amount) FROM orders") == [[100, 500]]
    assert q(eng, "SELECT AVG(amount) FROM orders") == [[300.0]]
    assert q(eng, "SELECT COUNT(amount) FROM orders") == [[5]]
    assert q(eng, "SELECT COUNT(DISTINCT region) FROM orders") == [[3]]


def test_aggregate_with_filter(eng):
    assert q(eng, "SELECT SUM(amount) FROM orders WHERE region = 'east'") \
        == [[900]]
    assert q(eng, "SELECT COUNT(*) FROM orders WHERE vip = true") == [[3]]


def test_aggregate_expression(eng):
    assert q(eng, "SELECT SUM(amount) / COUNT(*) FROM orders") == [[300]]


def test_group_by_count(eng):
    got = q(eng, "SELECT region, COUNT(*) FROM orders GROUP BY region")
    assert sorted(got) == [["east", 3], ["north", 1], ["west", 1]]


def test_group_by_sum(eng):
    got = q(eng, "SELECT region, SUM(amount) FROM orders GROUP BY region")
    assert sorted(got) == [["east", 900], ["north", 400], ["west", 200]]


def test_group_by_having(eng):
    got = q(eng, "SELECT region, COUNT(*) FROM orders GROUP BY region "
                 "HAVING COUNT(*) > 1")
    assert got == [["east", 3]]


def test_group_by_host_fallback_avg(eng):
    got = q(eng, "SELECT region, AVG(amount) FROM orders GROUP BY region")
    assert sorted(got) == [["east", 300.0], ["north", 400.0], ["west", 200.0]]


def test_group_by_int_column_fallback(eng):
    got = q(eng, "SELECT amount, COUNT(*) FROM orders GROUP BY amount "
                 "ORDER BY amount")
    assert got == [[100, 1], [200, 1], [300, 1], [400, 1], [500, 1]]


def test_order_by_limit(eng):
    got = q(eng, "SELECT _id FROM orders ORDER BY amount DESC LIMIT 2")
    assert got == [[5], [4]]


def test_order_by_alias_and_offset(eng):
    got = q(eng, "SELECT _id, amount AS a FROM orders ORDER BY a LIMIT 2 OFFSET 1")
    assert got == [[2, 200], [3, 300]]


def test_distinct(eng):
    got = q(eng, "SELECT DISTINCT segment FROM orders")
    assert sorted(got) == [["retail"], ["wholesale"]]


def test_projection_arithmetic(eng):
    got = q(eng, "SELECT _id, amount * 2 FROM orders WHERE _id = 1")
    assert got == [[1, 200]]


def test_where_host_fallback(eng):
    # arithmetic predicate has no bitmap form -> host filter
    got = q(eng, "SELECT _id FROM orders WHERE amount % 200 = 0")
    assert got == [[2], [4]]


def test_like(eng):
    got = q(eng, "SELECT _id FROM orders WHERE region LIKE 'e%'")
    assert got == [[1], [3], [5]]


def test_show_tables_columns(eng):
    assert q(eng, "SHOW TABLES") == [["orders"]]
    cols = dict(q(eng, "SHOW COLUMNS FROM orders"))
    assert cols["_id"] == "ID"
    assert cols["region"] == "STRING"
    assert cols["amount"] == "INT"
    assert cols["price"] == "DECIMAL(2)"
    assert cols["tags"] == "STRINGSET"


def test_alter_table(eng):
    eng.query("ALTER TABLE orders ADD COLUMN rating INT")
    assert "rating" in dict(q(eng, "SHOW COLUMNS FROM orders"))
    eng.query("ALTER TABLE orders DROP COLUMN rating")
    assert "rating" not in dict(q(eng, "SHOW COLUMNS FROM orders"))


def test_delete(eng):
    r = eng.query("DELETE FROM orders WHERE region = 'west'")
    assert r.changed == 1
    assert q(eng, "SELECT COUNT(*) FROM orders") == [[4]]
    assert q(eng, "SELECT _id FROM orders WHERE region = 'west'") == []


def test_delete_all(eng):
    eng.query("DELETE FROM orders")
    assert q(eng, "SELECT COUNT(*) FROM orders") == [[0]]


def test_insert_mutex_overwrite(eng):
    eng.query("INSERT INTO orders (_id, region) VALUES (1, 'south')")
    got = q(eng, "SELECT region FROM orders WHERE _id = 1")
    assert got == [["south"]]
    # old value gone (mutex semantics)
    assert q(eng, "SELECT _id FROM orders WHERE region = 'east'") == [[3], [5]]


def test_replace_resets_sets(eng):
    eng.query("REPLACE INTO orders (_id, tags) VALUES (1, ['white'])")
    got = q(eng, "SELECT tags FROM orders WHERE _id = 1")
    assert got == [[["white"]]]


def test_drop_table(eng):
    eng.query("DROP TABLE orders")
    assert q(eng, "SHOW TABLES") == []
    eng.query("DROP TABLE IF EXISTS orders")  # no error
    with pytest.raises(Exception):
        eng.query("DROP TABLE orders")


def test_create_keyed_table(eng):
    eng.query("CREATE TABLE people (_id STRING, age INT)")
    eng.query("INSERT INTO people (_id, age) VALUES ('alice', 30), ('bob', 40)")
    got = q(eng, "SELECT _id, age FROM people ORDER BY age")
    assert got == [["alice", 30], ["bob", 40]]
    got = q(eng, "SELECT _id FROM people WHERE age > 35")
    assert got == [["bob"]]


def test_select_no_table(eng):
    assert q(eng, "SELECT 1 + 2") == [[3]]


def test_timestamp_column(eng):
    eng.query("CREATE TABLE events (_id ID, at TIMESTAMP)")
    eng.query("INSERT INTO events (_id, at) VALUES (1, '2024-01-15T10:00:00Z')")
    got = q(eng, "SELECT at FROM events WHERE _id = 1")
    assert got == [["2024-01-15T10:00:00Z"]]
    got = q(eng, "SELECT _id FROM events WHERE at > '2024-01-01T00:00:00Z'")
    assert got == [[1]]


def test_bulk_insert_stream(eng):
    eng.query("CREATE TABLE bulk1 (_id ID, city STRING, pop INT)")
    data = "1,springfield,30000\n2,shelbyville,20000\n3,ogdenville,10000"
    r = eng.query(f"BULK INSERT INTO bulk1 (_id, city, pop) "
                  f"MAP (0 ID, 1 STRING, 2 INT) FROM '{data}' "
                  f"WITH FORMAT 'CSV' INPUT 'STREAM'")
    assert r.changed == 3
    got = q(eng, "SELECT _id, city, pop FROM bulk1 WHERE pop >= 20000 "
                 "ORDER BY pop DESC")
    assert got == [[1, "springfield", 30000], [2, "shelbyville", 20000]]


def test_parse_errors(eng):
    with pytest.raises(SQLError):
        eng.query("SELEC * FROM orders")
    with pytest.raises(SQLError):
        eng.query("SELECT FROM orders WHERE")
    with pytest.raises(Exception):
        eng.query("SELECT nosuchcol FROM orders")


# -- regressions from review ------------------------------------------------

def test_group_by_order_differs_from_schema_order(eng):
    # host fallback (AVG): group-key order must follow GROUP BY, not the
    # alphabetical scan schema
    got = q(eng, "SELECT segment, region, AVG(amount) FROM orders "
                 "GROUP BY segment, region")
    assert ["retail", "east", 300.0] in got
    assert ["wholesale", "east", 300.0] in got


def test_insert_default_columns_declared_order(eng):
    eng.query("CREATE TABLE declared (_id ID, name STRING, age INT)")
    eng.query("INSERT INTO declared VALUES (1, 'bob', 30)")
    assert q(eng, "SELECT name, age FROM declared") == [["bob", 30]]


def test_order_by_aggregate(eng):
    got = q(eng, "SELECT region, COUNT(*) FROM orders GROUP BY region "
                 "ORDER BY COUNT(*) DESC, region")
    assert got == [["east", 3], ["north", 1], ["west", 1]]
    # aggregate only referenced by ORDER BY (hidden column path)
    got = q(eng, "SELECT region FROM orders GROUP BY region "
                 "ORDER BY SUM(amount) DESC")
    assert got == [["east"], ["north"], ["west"]]


def test_delete_missing_record_rows_affected(eng):
    r = eng.query("DELETE FROM orders WHERE _id = 99")
    assert r.changed == 0


def test_neq_excludes_null(eng):
    eng.query("CREATE TABLE nulls (_id ID, name STRING, age INT)")
    eng.query("INSERT INTO nulls (_id, age) VALUES (2, 30)")
    eng.query("INSERT INTO nulls (_id, name, age) VALUES (3, 'x', 40)")
    # record 2 has NULL name: must not match != or NOT IN
    assert q(eng, "SELECT _id FROM nulls WHERE name != 'zzz'") == [[3]]
    assert q(eng, "SELECT _id FROM nulls WHERE name NOT IN ('zzz')") == [[3]]
    # BSI != also excludes null
    eng.query("INSERT INTO nulls (_id, name) VALUES (4, 'y')")
    assert q(eng, "SELECT _id FROM nulls WHERE age != 99") == [[2], [3]]


def test_not_three_valued_logic(eng):
    eng.query("CREATE TABLE n2 (_id ID, name STRING, age INT)")
    eng.query("INSERT INTO n2 (_id, age) VALUES (2, 30)")
    eng.query("INSERT INTO n2 (_id, name, age) VALUES (3, 'x', 40)")
    # NOT over NULL row behaves like != (De Morgan push-down)
    assert q(eng, "SELECT _id FROM n2 WHERE NOT name = 'zzz'") == [[3]]
    assert q(eng, "SELECT _id FROM n2 WHERE NOT (name = 'zzz' OR age = 30)") \
        == [[3]]
    assert q(eng, "SELECT _id FROM n2 WHERE age NOT BETWEEN 35 AND 50") == [[2]]
    assert q(eng, "SELECT _id FROM n2 WHERE NOT age BETWEEN 35 AND 50") == [[2]]
    assert q(eng, "SELECT _id FROM n2 WHERE NOT NOT age = 30") == [[2]]
    assert q(eng, "SELECT _id FROM n2 WHERE NOT name IS NULL") == [[3]]


def test_empty_ungrouped_host_aggregate(eng):
    got = q(eng, "SELECT COUNT(*), SUM(amount), AVG(amount) FROM orders "
                 "WHERE amount % 2 = 1")
    assert got == [[0, None, None]]


def test_distinct_numeric_aggregates(eng):
    eng.query("INSERT INTO orders (_id, region, amount) VALUES (6, 'east', 100)")
    # amounts now 100,200,300,400,500,100 -> distinct sum 1500, plain 1600
    assert q(eng, "SELECT SUM(amount) FROM orders") == [[1600]]
    assert q(eng, "SELECT SUM(DISTINCT amount) FROM orders") == [[1500]]
    assert q(eng, "SELECT AVG(DISTINCT amount) FROM orders") == [[300.0]]
    got = q(eng, "SELECT region, SUM(DISTINCT amount) FROM orders "
                 "GROUP BY region")
    assert ["east", 900] in got  # 100,300,500,100 -> distinct 900


def test_group_by_expression(eng):
    got = q(eng, "SELECT amount / 200, COUNT(*) FROM orders "
                 "GROUP BY amount / 200 ORDER BY amount / 200")
    # amounts 100..500 -> 0:1(100), 1:2(200,300), 2:2(400,500)
    assert got == [[0, 1], [1, 2], [2, 2]]


def test_bulk_insert_missing_values(eng):
    eng.query("CREATE TABLE bm (_id ID, a STRING, b INT)")
    data = "1,x,5\\n2,y"
    import pytest as _pt
    with _pt.raises(Exception):
        eng.query("BULK INSERT INTO bm (_id, a, b) MAP (0 ID, 1 STRING, 2 INT) "
                  "FROM '1,x,5\n2,y' WITH FORMAT 'CSV' INPUT 'STREAM'")
    r = eng.query("BULK INSERT INTO bm (_id, a, b) MAP (0 ID, 1 STRING, 2 INT) "
                  "FROM '1,x,5\n2,y' WITH FORMAT 'CSV' INPUT 'STREAM' "
                  "ALLOW_MISSING_VALUES")
    assert r.changed == 2
    assert q(eng, "SELECT b FROM bm WHERE _id = 2") == [[None]]


class TestDialectTail:
    """CREATE FUNCTION / MODEL, PREDICT, COPY (reference:
    sql3 CreateFunctionStatement + userdefinedfunctions.go [evaluation
    unsupported there too], parseCreateModelStatement, compilecopy.go
    [ships rows to another FeatureBase]; VERDICT r4 missing #6)."""

    def test_function_registry_and_refusal(self):
        from pilosa_tpu.sql.lexer import SQLError

        api = API()
        api.sql("create table ft (_id id, v int)")
        api.sql("insert into ft values (1, 5)")
        api.sql("create function f1 (@x int, @y string) returns int "
                "as begin end")
        # duplicate fails; IF NOT EXISTS is idempotent
        with pytest.raises(SQLError):
            api.sql("create function f1 (@x int) returns int as begin end")
        api.sql("create function if not exists f1 (@x int) returns int "
                "as begin end")
        with pytest.raises(SQLError, match="user defined functions"):
            api.sql("select f1(v) from ft")
        api.sql("drop function f1")
        with pytest.raises(SQLError):
            api.sql("drop function f1")
        api.sql("drop function if exists f1")
        assert api.sql("select v from ft").data == [[5]]

    def test_model_and_predict(self):
        from pilosa_tpu.sql.lexer import SQLError

        api = API()
        api.sql("create table mt (_id id, v int)")
        api.sql("create model m1 (v int) with budget 100")
        with pytest.raises(SQLError, match="PREDICT is not supported"):
            api.sql("predict using m1 select v from mt")
        with pytest.raises(SQLError, match="does not exist"):
            api.sql("predict using nosuch select v from mt")

    def test_copy_local(self):
        api = API()
        api.sql("create table csrc (_id id, v int, tags stringset)")
        api.sql("insert into csrc values (1, 5, ['a','b']), (2, 9, ['b']), "
                "(3, 2, null)")
        r = api.sql("copy csrc to cdst where v > 3")
        assert r.changed == 2
        assert api.sql("select _id, v from cdst").data == [[1, 5], [2, 9]]
        assert api.sql(
            "select count(*) from cdst where setcontains(tags, 'b')"
        ).data == [[2]]

    def test_copy_remote_over_client(self):
        from pilosa_tpu.server.http import serve

        src = API()
        src.sql("create table r1 (_id string, v int, s string)")
        src.sql("insert into r1 values ('a', 1, 'x'), ('b', 2, 'it''s')")
        dst = API()
        srv, _ = serve(dst, port=0, background=True)
        host, port = srv.server_address[:2]
        try:
            r = src.sql(f"copy r1 to r2 with url 'http://{host}:{port}'")
            assert r.changed == 2
            got = dst.sql("select _id, v, s from r2").data
            assert sorted(map(tuple, got)) == [
                ("a", 1, "x"), ("b", 2, "it's")]
        finally:
            srv.shutdown()
            srv.server_close()

    def test_tail_regressions(self):
        from pilosa_tpu.sql.lexer import SQLError

        api = API()
        # new statement keywords stay usable as column names
        api.sql("create table kw (_id id, url string, model int)")
        api.sql("insert into kw values (1, 'http://x', 7)")
        assert api.sql("select url, model from kw where model > 3"
                       ).data == [["http://x", 7]]
        # mixed-case function names normalize
        api.sql("create function MyFunc (@x int) returns int as begin end")
        with pytest.raises(SQLError, match="user defined functions"):
            api.sql("select myfunc(model) from kw")
        with pytest.raises(SQLError, match="already exists"):
            api.sql("create function MYFUNC (@x int) returns int "
                    "as begin end")
        api.sql("drop function myfunc")
        # drop model exists; drop table if exists still parses
        api.sql("create model mm (v int)")
        api.sql("drop model mm")
        api.sql("drop table if exists notthere")
        # JOIN over a derived table errors instead of silently dropping
        with pytest.raises(SQLError, match="derived table"):
            api.sql("select * from (select _id from kw) d "
                    "inner join kw on d._id = kw._id")
        # scientific-notation floats survive the remote-insert format
        from pilosa_tpu.sql.engine import SQLEngine
        txt = SQLEngine._insert_sql("t", ["_id", "d"], [[1, 1e-06]])
        api.sql("create table t (_id id, d decimal(6))")
        api.sql(txt)
        assert api.sql("select d from t").data == [[1e-06]]


class TestQuantumEdges:
    """Round-5 review findings on the quantum SQL surface."""

    def _mk(self):
        api = API()
        api.sql("create table q (_id id, ids1 idsetq timequantum 'YMD', "
                "ss1 stringsetq timequantum 'YMD')")
        return api

    def test_replace_with_tuple_value(self):
        api = self._mk()
        api.sql("replace into q (_id, ids1) values "
                "(1, {'2022-01-02T00:00:00Z', [5]})")
        assert api.sql(
            "select _id from q where rangeq(ids1, '2022-01-01T00:00:00Z',"
            " '2022-02-01T00:00:00Z')").data == [[1]]
        # and no repr-garbage row keys were written
        api2 = self._mk()
        api2.sql("replace into q (_id, ss1) values "
                 "(1, {'2022-01-02T00:00:00Z', ['a']})")
        rows = api2.query("q", "Rows(ss1)")[0]
        assert rows == ["a"], rows

    def test_empty_tuple_keeps_record_alive(self):
        api = self._mk()
        api.sql("insert into q (_id, ids1) values "
                "(3, {'2022-01-02T00:00:00Z', []})")
        assert api.sql("select count(*) from q").data == [[1]]

    def test_ranged_unionrows_honors_limit(self):
        api = self._mk()
        api.sql("insert into q (_id, ids1) values "
                "(1, {'2022-01-02T00:00:00Z', [1]}), "
                "(2, {'2022-01-03T00:00:00Z', [2]})")
        full = api.query(
            "q", "Count(UnionRows(Rows(ids1, from='2022-01-01T00:00:00Z',"
            " to='2022-02-01T00:00:00Z')))")[0]
        limited = api.query(
            "q", "Count(UnionRows(Rows(ids1, from='2022-01-01T00:00:00Z',"
            " to='2022-02-01T00:00:00Z', limit=1)))")[0]
        assert full == 2 and limited == 1

    def test_rangeq_bad_bound_is_sql_error(self):
        from pilosa_tpu.sql.lexer import SQLError

        api = self._mk()
        for bad in ("'garbage'", "123"):
            with pytest.raises(SQLError, match="not a timestamp"):
                api.sql(f"select _id from q where rangeq(ids1, {bad})")
