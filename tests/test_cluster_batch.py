"""Cluster fan-out batching tests: the per-node remote-leg coalescer
(cluster/batch.py), the /internal/query-batch wire path, the shared
arrival-window policy (sched/window.py), keep-alive connection pooling
(client.py), and end-to-end behavior over LocalCluster — bit-identity
vs the unbatched oracle, partial-batch failover under seeded FaultPlan
chaos scoped to op="query_batch", breaker-veto rerouting of whole node
batches, and the cluster_batch_* metrics exposition.

scripts/tier1.sh re-runs this file with PILOSA_TPU_CLUSTER_BATCH=1 and
a fixed fault seed; every test must hold for ANY seed (prob rules are
the only seed-steered surface and none are used here)."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    FaultPlan, InternalClient, LegCancelled, LocalCluster, NodeBatcher,
    NodeDownError, RemoteError, Resilience,
)
from pilosa_tpu.cluster.batch import _BatchToken
from pilosa_tpu.cluster.resilience import BREAKER_OPEN, CancellationToken
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs import tracing as T
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.sched.window import ArrivalWindow
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _fill(target, index="cb"):
    """Identical dataset on any API/ClusterNode: 5 shards, 3 rows."""
    target.create_index(index)
    target.create_field(index, "f")
    rows, cols = [], []
    for c in range(0, 5 * SHARD_WIDTH, SHARD_WIDTH // 4):
        rows.append((c // 100) % 3)
        cols.append(c)
    target.import_bits(index, "f", rows=rows, cols=cols)
    return index


def _remote_primary(co, index):
    ex = co.executor
    snap = ex._snapshot_fn()
    by_node = ex._assign(snap, index, sorted(ex._shards_fn(index)), set())
    return next(nid for nid in by_node if nid != ex.node_id)


class FakeClient:
    """query_node_batch stand-in: records calls, demuxes via handler."""

    def __init__(self, handler=None, block=None):
        self.calls = []
        self.lock = threading.Lock()
        self.block = block  # optional event the send waits on
        self.handler = handler or (lambda entries: [
            {"results": [["slot", e["index"], e["query"],
                          tuple(e["shards"])]]} for e in entries])

    def query_node_batch(self, node, entries, token=None):
        with self.lock:
            self.calls.append((node.id, [dict(e) for e in entries], token))
        if self.block is not None:
            self.block.wait(5.0)
        return self.handler(entries)


NODE = Node(id="peer0", uri="http://peer0")


class TestArrivalWindow:
    def test_non_adaptive_returns_fixed_window(self):
        w = ArrivalWindow(0.25, adaptive=False)
        assert w.window_s() == 0.25
        w.observe(1.0)
        w.observe(1.001)
        assert w.window_s() == 0.25

    def test_idle_collapses_to_min_and_bursts_earn_max(self):
        w = ArrivalWindow(0.0, adaptive=True, window_min_s=0.001,
                          window_max_s=0.01, max_batch=10)
        assert w.window_s() == 0.001  # no gap observed yet
        t = 0.0
        for _ in range(50):  # 1 kHz arrivals: gap far under max/max_batch
            w.observe(t)
            t += 0.001
        assert w.window_s() == pytest.approx(0.01)
        for _ in range(50):  # 1 Hz arrivals: collapse back toward min
            w.observe(t)
            t += 1.0
        assert w.window_s() == pytest.approx(0.001)

    def test_scheduler_delegates_to_shared_policy(self):
        from pilosa_tpu.sched import QueryScheduler

        sched = QueryScheduler(None, adaptive_window=True,
                               window_min_ms=0.2, window_max_ms=5.0)
        try:
            assert isinstance(sched._arrival, ArrivalWindow)
            assert sched.current_window_ms() == pytest.approx(0.2)
        finally:
            sched.close()


class TestBatchToken:
    def test_cancelled_only_when_every_member_is(self):
        a, b = CancellationToken(), CancellationToken()
        bt = _BatchToken([a, b])
        assert not bt.cancelled
        a.cancel()
        assert not bt.cancelled  # b keeps the shared wire call alive
        b.cancel()
        assert bt.cancelled
        assert bt.wait(10.0) is True  # returns promptly once cancelled

    def test_member_without_token_pins_uncancellable(self):
        a = CancellationToken()
        a.cancel()
        bt = _BatchToken([a, None])
        assert not bt.cancelled
        assert bt.wait(0.01) is False

    def test_timeout_is_laxest_member(self):
        bt = _BatchToken([CancellationToken(timeout_s=0.5),
                          CancellationToken(timeout_s=2.0)])
        assert bt.timeout_s == 2.0
        # any member without a timeout pins the batch untimed
        bt = _BatchToken([CancellationToken(timeout_s=0.5),
                          CancellationToken()])
        assert bt.timeout_s is None


class TestNodeBatcher:
    def _batcher(self, client, reg=None, **kw):
        kw.setdefault("window_ms", 20.0)
        kw.setdefault("adaptive_window", False)
        return NodeBatcher(client, registry=reg or MetricsRegistry(), **kw)

    def test_solo_leg_ships_as_batch_of_one(self):
        fc = FakeClient()
        b = self._batcher(fc, window_ms=0.0)
        out = b.run(NODE, "i", "Count(Row(f=0))", [1, 2])
        assert out == [["slot", "i", "Count(Row(f=0))", (1, 2)]]
        assert len(fc.calls) == 1
        assert fc.calls[0][1] == [
            {"index": "i", "query": "Count(Row(f=0))", "shards": [1, 2]}]
        # a single-leg batch carries the leg's own token, not a wrapper
        assert fc.calls[0][2] is None

    def test_concurrent_legs_coalesce_into_one_rpc(self):
        fc = FakeClient()
        reg = MetricsRegistry()
        b = self._batcher(fc, reg, max_batch=8, window_ms=250.0)
        with ThreadPoolExecutor(8) as pool:
            outs = list(pool.map(
                lambda i: b.run(NODE, "i", f"q{i}", [i]), range(8)))
        # max_batch reached => the window never has to expire
        assert len(fc.calls) == 1
        assert len(fc.calls[0][1]) == 8
        for i, out in enumerate(outs):  # demux preserves per-leg identity
            assert out == [["slot", "i", f"q{i}", (i,)]]
        h = reg.histogram(M.METRIC_CLUSTER_BATCH_SIZE)
        assert h["count"] == 1 and h["sum"] == 8.0
        assert reg.value(M.METRIC_CLUSTER_BATCHED_RPCS, node="peer0") == 1.0

    def test_queue_beyond_max_batch_ships_in_waves(self):
        fc = FakeClient()
        b = self._batcher(fc, max_batch=4, window_ms=40.0)
        with ThreadPoolExecutor(10) as pool:
            outs = list(pool.map(
                lambda i: b.run(NODE, "i", f"q{i}", [i]), range(10)))
        assert all(outs[i] == [["slot", "i", f"q{i}", (i,)]]
                   for i in range(10))
        assert 3 <= len(fc.calls) <= 10
        assert all(len(c[1]) <= 4 for c in fc.calls)

    def test_per_entry_error_hits_only_its_leg(self):
        def handler(entries):
            out = []
            for e in entries:
                if e["query"] == "bad":
                    out.append({"error": "no such field", "status": 404})
                else:
                    out.append({"results": [["ok", e["query"]]]})
            return out

        fc = FakeClient(handler)
        reg = MetricsRegistry()
        b = self._batcher(fc, reg, max_batch=3, window_ms=250.0)
        with ThreadPoolExecutor(3) as pool:
            futs = [pool.submit(b.run, NODE, "i", q, [0])
                    for q in ("good1", "bad", "good2")]
            results, errors = [], []
            for f in futs:
                try:
                    results.append(f.result(timeout=5.0))
                except RemoteError as e:
                    errors.append(e)
        assert len(fc.calls) == 1  # one RPC carried all three
        assert sorted(r[0][1] for r in results) == ["good1", "good2"]
        assert len(errors) == 1 and errors[0].status == 404
        assert reg.value(M.METRIC_CLUSTER_BATCH_DEMUX_FAILURES,
                         node="peer0", why="query") == 1.0

    def test_transport_failure_fails_every_member(self):
        class DownClient:
            def query_node_batch(self, node, entries, token=None):
                raise NodeDownError("peer gone")

        reg = MetricsRegistry()
        b = self._batcher(DownClient(), reg, max_batch=2, window_ms=250.0)
        with ThreadPoolExecutor(2) as pool:
            futs = [pool.submit(b.run, NODE, "i", f"q{i}", [i])
                    for i in range(2)]
            for f in futs:
                with pytest.raises(NodeDownError):
                    f.result(timeout=5.0)
        assert reg.value(M.METRIC_CLUSTER_BATCH_DEMUX_FAILURES,
                         node="peer0", why="transport") == 2.0

    def test_slot_count_mismatch_is_a_demux_error(self):
        fc = FakeClient(handler=lambda entries: [])
        b = self._batcher(fc, window_ms=0.0)
        with pytest.raises(RemoteError, match="batch demux"):
            b.run(NODE, "i", "q", [0])

    def test_cancelled_pending_leg_withdraws(self):
        tok = CancellationToken()
        tok.cancel()
        fc = FakeClient()
        b = self._batcher(fc)
        with pytest.raises(LegCancelled):
            b.run(NODE, "i", "q", [0], token=tok)
        assert fc.calls == []  # withdrawn before any wire send
        with b._lock:
            assert b._slots["peer0"].pending == []

    def test_distinct_nodes_never_share_a_batch(self):
        fc = FakeClient()
        b = self._batcher(fc, max_batch=4, window_ms=30.0)
        other = Node(id="peer1", uri="http://peer1")
        with ThreadPoolExecutor(4) as pool:
            futs = [pool.submit(b.run, n, "i", f"q{i}", [i])
                    for i, n in enumerate([NODE, other, NODE, other])]
            for f in futs:
                f.result(timeout=5.0)
        assert {c[0] for c in fc.calls} == {"peer0", "peer1"}
        for nid, entries, _tok in fc.calls:
            assert all(q["query"] in
                       (("q0", "q2") if nid == "peer0" else ("q1", "q3"))
                       for q in entries)


class TestQueryRemoteBatch:
    """The serving side: ClusterNode.query_remote_batch demuxes into the
    remote executor's execute_many superset-merge."""

    def test_mixed_indexes_preserve_slot_order(self):
        c = LocalCluster(1)
        try:
            n = c.coordinator
            _fill(n, "qa")
            _fill(n, "qb")
            out = n.query_remote_batch([
                {"index": "qa", "query": "Count(Row(f=0))", "shards": [0]},
                {"index": "qb", "query": "Count(Row(f=1))", "shards": [1]},
                {"index": "qa", "query": "Count(Row(f=2))", "shards": [2]},
            ])
            assert len(out) == 3
            solo = [n.query_remote("qa", "Count(Row(f=0))", [0]),
                    n.query_remote("qb", "Count(Row(f=1))", [1]),
                    n.query_remote("qa", "Count(Row(f=2))", [2])]
            assert [o["results"] for o in out] == solo
        finally:
            c.close()

    def test_bad_entry_gets_error_slot_not_batch_failure(self):
        c = LocalCluster(1)
        try:
            n = c.coordinator
            _fill(n, "qe")
            out = n.query_remote_batch([
                {"index": "qe", "query": "Count(Row(f=0))", "shards": [0]},
                {"index": "nope", "query": "Count(Row(f=0))",
                 "shards": [0]},
            ])
            assert "results" in out[0]
            assert out[1]["status"] == 404 and "error" in out[1]
        finally:
            c.close()


class TestBatchedClusterEndToEnd:
    def test_bit_identical_to_unbatched_oracle_with_rpc_reduction(self):
        oracle = API()
        _fill(oracle, "e2")
        c = LocalCluster(3, replica_n=2, cluster_batch={})
        try:
            co = c.coordinator
            _fill(co, "e2")
            queries = [f"Count(Row(f={i % 3}))" for i in range(24)]
            want = [oracle.query("e2", q) for q in queries]
            with ThreadPoolExecutor(12) as pool:
                got = list(pool.map(lambda q: co.query("e2", q), queries))
            assert got == want
            ops = co.client.op_counts
            assert ops.get("query", 0) == 0  # every read leg batched
            # 24 queries x 2 remote nodes = 48 unbatched legs; batching
            # must beat that by a wide margin
            assert 0 < ops["query_batch"] <= 24
        finally:
            c.close()

    def test_env_flag_attaches_batcher_at_construction(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_BATCH", "1")
        c = LocalCluster(1)
        try:
            assert isinstance(c.coordinator.batcher, NodeBatcher)
        finally:
            c.close()
        monkeypatch.delenv("PILOSA_TPU_CLUSTER_BATCH")
        c = LocalCluster(1)
        try:
            assert c.coordinator.batcher is None
        finally:
            c.close()

    def test_config_section_round_trips(self, tmp_path):
        from pilosa_tpu.config import Config

        p = tmp_path / "c.toml"
        p.write_text("[cluster.batch]\nenabled = true\nmax-batch = 7\n"
                     "window-ms = 1.5\nadaptive-window = false\n")
        cfg = Config.from_sources(toml_path=str(p), env={})
        assert cfg.cluster_batch_enabled is True
        assert cfg.cluster_batch_max_batch == 7
        assert cfg.cluster_batch_window_ms == 1.5
        assert cfg.cluster_batch_adaptive_window is False
        b = NodeBatcher.from_config(None, cfg)
        assert b.max_batch == 7
        assert b._arrival.adaptive is False
        assert b._arrival.fixed_window_s == pytest.approx(0.0015)

    def test_remote_leg_cache_fills_from_batch_partials(self):
        c = LocalCluster(3, cluster_batch={})
        try:
            co = c.coordinator
            _fill(co, "cc")
            co.enable_cache(ttl_ms=60000.0)
            q = "Count(Row(f=0))"
            want = co.query("cc", q)
            sent = co.client.op_counts.get("query_batch", 0)
            assert co.query("cc", q) == want
            # the repeat run's remote legs hit the per-leg cache entries
            # the batch RPC filled — no new wire sends
            assert co.client.op_counts.get("query_batch", 0) == sent
        finally:
            c.close()


class TestBatchedChaos:
    """FaultPlan chaos scoped op="query_batch" over batched fan-outs."""

    def _fixture(self, plan, **harness_kw):
        return LocalCluster(
            3, replica_n=2,
            client_factory=lambda i: InternalClient(retries=0,
                                                    fault_plan=plan),
            cluster_batch={}, **harness_kw)

    def test_partial_batch_failover_to_replica_rank_1(self):
        plan = FaultPlan()
        c = self._fixture(plan)
        try:
            oracle = API()
            _fill(oracle, "pf")
            co = c.coordinator
            _fill(co, "pf")
            q = "Count(Row(f=0))"
            want = oracle.query("pf", q)
            assert co.query("pf", q) == want  # warm, fault-free
            victim = _remote_primary(co, "pf")
            downs = []
            orig = co.executor._on_node_down
            co.executor._on_node_down = lambda nid: (downs.append(nid),
                                                     orig(nid))
            try:
                # drop exactly the next BATCH RPC to the victim: its legs
                # re-target rank 1; the other node's batch is untouched
                plan.drop(victim, first=plan.seen(victim), count=1,
                          op="query_batch")
                assert co.query("pf", q) == want
                assert downs == [victim]
            finally:
                co.executor._on_node_down = orig
                plan.clear()
            assert co.query("pf", q) == want  # healthy again
        finally:
            c.close()

    def test_breaker_veto_reroutes_whole_node_batch(self):
        plan = FaultPlan()
        c = self._fixture(plan)
        try:
            oracle = API()
            _fill(oracle, "bv")
            co = c.coordinator
            _fill(co, "bv")
            q = "Count(Row(f=1))"
            want = oracle.query("bv", q)
            res = co.enable_resilience(hedge=False, breaker_threshold=1,
                                       breaker_open_ms=60000.0)
            try:
                assert co.query("bv", q) == want  # warm, fault-free
                victim = _remote_primary(co, "bv")
                # park an idle pooled socket so the breaker's open
                # transition has something to evict
                assert co.client.pool._idle.get(victim)
                plan.drop(victim, first=plan.seen(victim), count=1,
                          op="query_batch")
                assert co.query("bv", q) == want  # failover opens breaker
                plan.clear()
                assert res.breaker.state(victim) == BREAKER_OPEN
                # breaker-aware eviction dropped the victim's keep-alives
                assert not co.client.pool._idle.get(victim)
                # veto at assign time: the whole node batch reroutes to
                # replicas without a single RPC reaching the victim
                before = plan.seen(victim)
                plan.delay(victim, 0.0, first=10**9)  # arm counting only
                assert co.query("bv", q) == want
                assert plan.seen(victim) == before
            finally:
                plan.clear()
                co.disable_resilience()
        finally:
            c.close()

    def test_hedged_batch_straggler_matches_oracle(self):
        plan = FaultPlan()
        c = LocalCluster(3, replica_n=2, fault_plan=plan, cluster_batch={})
        try:
            oracle = API()
            _fill(oracle, "hx")
            co = c.coordinator
            _fill(co, "hx")
            q = "Count(Row(f=0))"
            want = oracle.query("hx", q)
            reg = MetricsRegistry()
            co.enable_resilience(registry=reg, hedge_min_ms=1.0,
                                 breaker_threshold=1 << 30)
            try:
                for _ in range(3):  # warm latency windows, fault-free
                    assert co.query("hx", q) == want
                victim = _remote_primary(co, "hx")
                plan.delay(victim, 2.0, op="query_batch")
                t0 = time.monotonic()
                got = co.query("hx", q)
                elapsed = time.monotonic() - t0
                plan.clear()
                assert got == want  # bit-identical despite the straggler
                assert elapsed < 1.6  # the hedged batch beat the delay
                assert reg.value(M.METRIC_CLUSTER_HEDGES) >= 1.0
            finally:
                plan.clear()
                co.disable_resilience()
        finally:
            c.close()


class TestCancelledLoserSpans:
    def test_hedge_loser_span_is_tagged_cancelled(self):
        prev = T.get_tracer()
        T.set_tracer(T.Tracer(enabled=True, registry=MetricsRegistry()))
        try:
            res = Resilience(registry=MetricsRegistry(), hedge_min_ms=1.0,
                             hedge_max_ms=1.0)

            def run_remote(node, shards, token):
                if node == "A":  # parked primary loses to the hedge
                    if token.wait(10.0):
                        raise LegCancelled("parked leg cancelled")
                return ("part", node)

            with T.get_tracer().start_trace("q") as root:
                parts, failed = res.run_legs(
                    {"a": [1]}, {"a": "A", "b": "B"}, run_remote,
                    lambda s, r: {"b": list(s)})
            assert parts == [("part", "B")] and failed == []
            legs = {s.tags.get("node"): s for s in root.children
                    if s.name == "cluster.leg"}
            assert legs["b"].tags.get("hedge_won") is True
            loser = legs["a"]
            assert loser.tags.get("hedge_won") is False
            assert loser.tags.get("cancelled") is True  # terminal tag
        finally:
            T.set_tracer(prev)

    def test_batched_leg_span_carries_batch_tags(self):
        prev = T.get_tracer()
        T.set_tracer(T.Tracer(enabled=True, registry=MetricsRegistry()))
        try:
            fc = FakeClient()
            b = NodeBatcher(fc, registry=MetricsRegistry(), window_ms=0.0,
                            adaptive_window=False)
            with T.get_tracer().start_trace("q") as root:
                with T.get_tracer().start_span("cluster.leg",
                                               node="peer0") as leg:
                    b.run(NODE, "i", "q0", [0])
            assert leg.tags.get("batched") is True
            assert leg.tags.get("batch_queries") == 1
            batch_spans = [s for s in leg.children
                           if s.name == "cluster.batch"]
            assert len(batch_spans) == 1
            assert batch_spans[0].tags == {"node": "peer0", "queries": 1}
        finally:
            T.set_tracer(prev)


class TestConnPool:
    def test_keepalive_reuse_across_requests(self):
        c = LocalCluster(2)
        try:
            co = c.coordinator
            _fill(co, "ka")
            q = "Count(Row(f=0))"
            first = co.query("ka", q)
            for _ in range(3):
                assert co.query("ka", q) == first
            pool = co.client.pool
            assert pool.hits > 0  # later legs rode pooled sockets
            # the peer's idle sockets are bounded by per_key
            assert all(len(v) <= pool.per_key
                       for v in pool._idle.values())
        finally:
            c.close()

    def test_evict_closes_idle_sockets(self):
        c = LocalCluster(2)
        try:
            co = c.coordinator
            _fill(co, "ev")
            co.query("ev", "Count(Row(f=0))")
            victim = next(iter(co.client.pool._idle))
            n = co.client.evict_node(victim)
            assert n >= 1
            assert not co.client.pool._idle.get(victim)
        finally:
            c.close()

    def test_stale_pooled_socket_gets_free_fresh_retry(self):
        c = LocalCluster(2)
        try:
            co = c.coordinator
            _fill(co, "st")
            q = "Count(Row(f=0))"
            want = co.query("st", q)
            # sabotage every idle socket: close the server side's view by
            # shutting the sockets down locally — the next use fails at
            # send/status-line and must transparently retry fresh
            for conns in co.client.pool._idle.values():
                for conn in conns:
                    if conn.sock is not None:
                        conn.sock.close()
            assert co.query("st", q) == want
        finally:
            c.close()


class TestBatchMetricsExposition:
    def test_prometheus_text_exposes_batch_series(self):
        reg = MetricsRegistry()
        reg.observe_bucketed(M.METRIC_CLUSTER_BATCH_SIZE, 6.0,
                             M.CLUSTER_BATCH_SIZE_BUCKETS)
        reg.count(M.METRIC_CLUSTER_BATCHED_RPCS, node="n1")
        reg.count(M.METRIC_CLUSTER_BATCH_DEMUX_FAILURES, node="n1",
                  why="transport")
        text = reg.prometheus_text()
        assert "cluster_batch_size_bucket" in text
        assert 'cluster_batched_rpcs_total{node="n1"} 1' in text
        assert ('cluster_batch_demux_failures_total'
                '{node="n1",why="transport"} 1') in text

    def test_end_to_end_batch_rpcs_are_counted(self):
        c = LocalCluster(3, cluster_batch={})
        try:
            co = c.coordinator
            _fill(co, "mx")
            base = M.REGISTRY.value(M.METRIC_CLUSTER_BATCHED_RPCS,
                                    node="node1") or 0.0
            co.query("mx", "Count(Row(f=0))")
            after = M.REGISTRY.value(M.METRIC_CLUSTER_BATCHED_RPCS,
                                     node="node1") or 0.0
            assert after >= base + 1.0
        finally:
            c.close()
