"""CLI, config system, backup/restore/chksum, fbsql shell.

Reference analogs: ctl/backup_test.go round-trips, server/config tests,
cli/ tests.
"""

import io
import json
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.config import Config
from pilosa_tpu.ctl.cli import build_parser, main
from pilosa_tpu.ctl.fbsql import Shell
from pilosa_tpu.server.http import serve


@pytest.fixture
def server():
    api = API()
    srv, _ = serve(api, port=0, background=True)
    yield api, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def fill(api):
    api.create_index("b", {"keys": False})
    api.create_field("b", "f")
    api.create_field("b", "n", {"type": "int"})
    api.query("b", "Set(1, f=2)Set(9, f=2)Set(1, n=77)")
    api.import_dataframe("b", 0, [1, 9], {"fare": [1.5, 2.5]})
    api.create_index("k", {"keys": True})
    api.create_field("k", "g", {"keys": True})
    api.query("k", 'Set("alice", g="admin")')


class TestConfig:
    def test_layering(self, tmp_path):
        toml = tmp_path / "c.toml"
        toml.write_text('port = 7000\ndata-dir = "/x"\n[auth]\nenable = true\n')
        cfg = Config.from_sources(
            toml_path=str(toml),
            env={"PILOSA_TPU_PORT": "8000", "PILOSA_TPU_PEERS": "a,b"},
            flags={"bind": "0.0.0.0", "port": None})
        assert cfg.port == 8000          # env beats toml
        assert cfg.data_dir == "/x"      # toml beats default
        assert cfg.auth_enable is True   # [section] key flattening
        assert cfg.peers == ["a", "b"]   # env list parsing
        assert cfg.bind == "0.0.0.0"     # flag beats all
        # None flags don't override
        assert Config.from_sources(flags={"port": None}).port == 10101

    def test_generate_config_roundtrip(self, tmp_path):
        text = Config().to_toml()
        p = tmp_path / "gen.toml"
        p.write_text(text)
        assert Config.from_sources(toml_path=str(p)) == Config()

    def test_tenant_stanzas(self, tmp_path):
        toml = tmp_path / "t.toml"
        toml.write_text(
            "port = 7000\n"
            "[tenants.alpha]\nqps = 50\ncache-bytes = 4096\nweight = 3.0\n"
            "[tenants.beta]\ningest-rows-s = 1000\n")
        cfg = Config.from_sources(toml_path=str(toml))
        assert cfg.tenants_overrides == {
            "alpha": {"qps": 50, "cache_bytes": 4096, "weight": 3.0},
            "beta": {"ingest_rows_s": 1000}}
        # per-tenant stanzas survive to_toml -> from_sources
        p = tmp_path / "gen.toml"
        p.write_text(cfg.to_toml())
        assert Config.from_sources(toml_path=str(p)) == cfg

    def test_tenant_stanzas_applied_at_enable(self, tmp_path):
        toml = tmp_path / "t.toml"
        toml.write_text(
            "[tenants.alpha]\nqps = 50\ncache-bytes = 4096\nweight = 3.0\n")
        cfg = Config.from_sources(toml_path=str(toml))
        api = API()
        api.enable_cache()
        api.enable_tenants(config=cfg)
        reg = api.tenants
        assert reg.cache_quota_for("alpha") == 4096
        # unconfigured tenants fall back to the global default
        assert reg.cache_quota_for("nobody") == reg.cache_quota_bytes
        assert api.cache.tenant_quota_of("alpha") == 4096


class TestBackupRestore:
    def test_tar_roundtrip_between_servers(self, server):
        api, host = server
        fill(api)
        want_sum = api.checksum()
        # backup over HTTP
        with urllib.request.urlopen(host + "/internal/backup.tar") as r:
            blob = r.read()
        # restore into a second, different server with junk pre-state
        api2 = API()
        api2.create_index("junk")
        api2.restore_tar(io.BytesIO(blob))
        assert "junk" not in api2.holder.indexes
        assert api2.query("b", "Row(f=2)")[0].columns == [1, 9]
        assert api2.query("b", "Sum(field=n)")[0].val == 77
        assert api2.query("b", 'Apply("sum(fare)")')[0].value == pytest.approx(4.0)
        assert api2.query("k", 'Row(g="admin")')[0].keys == ["alice"]
        assert api2.checksum() == want_sum

    def test_restore_into_durable_server(self, server, tmp_path):
        api, host = server
        fill(api)
        buf = io.BytesIO()
        api.backup_tar(buf)
        api3 = API(str(tmp_path))
        api3.restore_tar(io.BytesIO(buf.getvalue()))
        del api3
        api4 = API(str(tmp_path))  # restored state is durable
        assert api4.query("b", "Row(f=2)")[0].columns == [1, 9]
        assert api4.checksum() == api.checksum()

    def test_checksum_changes_with_data(self, server):
        api, _ = server
        fill(api)
        a = api.checksum()
        api.query("b", "Set(5, f=2)")
        assert api.checksum() != a


class TestCLI:
    def test_generate_config_cmd(self, capsys):
        assert main(["generate-config"]) == 0
        assert "data-dir" in capsys.readouterr().out

    def test_backup_restore_chksum_cmds(self, server, tmp_path, capsys):
        api, host = server
        fill(api)
        out = tmp_path / "b.tar.gz"
        assert main(["backup", "--host", host, "--output", str(out)]) == 0
        assert out.stat().st_size > 0
        assert main(["chksum", "--host", host]) == 0
        sum1 = capsys.readouterr().out.strip()
        assert sum1 == api.checksum()
        # wipe and restore over HTTP
        api.delete_index("b")
        assert main(["restore", "--host", host, "--source", str(out)]) == 0
        assert api.query("b", "Row(f=2)")[0].columns == [1, 9]

    def test_import_export_cmds(self, server, tmp_path, capsys):
        api, host = server
        api.create_index("ie")
        api.create_field("ie", "f")
        api.create_field("ie", "v", {"type": "int"})
        csvf = tmp_path / "in.csv"
        csvf.write_text("1,10\n1,11\n2,10\n")
        assert main(["import", "--host", host, "--index", "ie",
                     "--field", "f", str(csvf)]) == 0
        assert api.query("ie", "Row(f=1)")[0].columns == [10, 11]
        vals = tmp_path / "vals.csv"
        vals.write_text("10,50\n11,-3\n")
        assert main(["import", "--host", host, "--index", "ie",
                     "--field", "v", "--field-type", "int", str(vals)]) == 0
        assert api.query("ie", "Sum(field=v)")[0].val == 47
        assert main(["export", "--host", host, "--index", "ie",
                     "--field", "f"]) == 0
        lines = sorted(capsys.readouterr().out.strip().splitlines())
        assert lines == ["1,10", "1,11", "2,10"]


class TestFbsql:
    def test_shell_statements_and_meta(self, server):
        api, host = server
        api.create_index("s1")
        api.create_field("s1", "f")
        api.query("s1", "Set(1, f=1)")
        stdin = io.StringIO(
            "select count(*) from s1\n"
            "\\dt\n"
            "\\timing\n"
            "select _id from s1\n"
            "bogus sql here\n"
            "\\q\n")
        out = io.StringIO()
        assert Shell(host=host, stdin=stdin, stdout=out).run() == 0
        text = out.getvalue()
        assert "count" in text
        assert "s1" in text          # \dt listing
        assert "Timing is on." in text
        assert "error:" in text      # bad SQL surfaced, shell kept going


class TestRestoreSafety:
    def test_restore_never_unpickles_wal(self, tmp_path):
        """A wal.log inside a backup tar is untrusted input: legitimate
        backups are checkpoint-complete and contain no WAL, so restore
        must load the snapshot only — never pickle-replay (advisor r1
        medium: arbitrary code execution via crafted backup)."""
        import pickle
        import tarfile

        api = API()
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(3, f=1)")
        buf = io.BytesIO()
        api.backup_tar(buf)

        class Evil:
            def __reduce__(self):
                marker = str(tmp_path / "pwned")
                return (open, (marker, "w"))

        # graft a malicious wal.log into the archive
        src = io.BytesIO(buf.getvalue())
        out = io.BytesIO()
        with tarfile.open(fileobj=src, mode="r|*") as tin, \
                tarfile.open(fileobj=out, mode="w|gz") as tout:
            for m in tin:
                tout.addfile(m, tin.extractfile(m) if m.isfile() else None)
            payload = pickle.dumps(Evil())
            rec = len(payload).to_bytes(8, "little") + payload
            info = tarfile.TarInfo("./indexes/i/wal.log")
            info.size = len(rec)
            tout.addfile(info, io.BytesIO(rec))

        api2 = API()
        api2.restore_tar(io.BytesIO(out.getvalue()))
        assert not (tmp_path / "pwned").exists(), "restore unpickled a WAL"
        assert api2.query("i", "Row(f=1)")[0].columns == [3]


class TestDatagen:
    def test_scenarios_ingest_in_process(self):
        from pilosa_tpu.api import API
        from pilosa_tpu.ingest.datagen import scenario, scenarios
        from pilosa_tpu.ingest.ingest import Ingester

        assert {"customer", "bank", "equipment",
                "kitchen-sink"} <= set(scenarios())
        api = API()
        n = Ingester(api, "cust", scenario("customer", rows=200)).run()
        assert n == 200
        # deterministic: same seed, same data
        api2 = API()
        Ingester(api2, "cust", scenario("customer", rows=200)).run()
        assert api.query("cust", "Sum(field=ltv)")[0].val == \
            api2.query("cust", "Sum(field=ltv)")[0].val
        assert api.query("cust", "Count(All())")[0] == 200

    def test_datagen_cli_remote(self):
        import sys

        from pilosa_tpu.api import API
        from pilosa_tpu.ctl.cli import main
        from pilosa_tpu.server.http import serve

        api = API()
        srv, _ = serve(api, port=0, background=True)
        try:
            base = f"http://{srv.server_address[0]}:{srv.server_address[1]}"
            rc = main(["datagen", "--scenario", "bank", "--rows", "300",
                       "--index", "txns", "--host", base])
            assert rc == 0
            assert api.query("txns", "Count(All())")[0] == 300
            top = api.query("txns", "TopN(category, n=1)")[0]
            assert top.pairs[0].count > 0
        finally:
            srv.shutdown()
            srv.server_close()


class TestQueryLogger:
    def test_query_log_records_pql_and_sql(self, tmp_path):
        from pilosa_tpu.api import API
        from pilosa_tpu.obs.logger import CaptureLogger

        api = API()
        api.set_query_logger(str(tmp_path / "queries.jsonl"))
        api.create_index("t")
        api.create_field("t", "f", {"type": "set"})
        api.query("t", "Set(1, f=2)")
        api.query("t", "Count(Row(f=2))")
        api.sql("select count(*) from t")
        try:
            api.query("t", "Bogus(")
        except Exception:
            pass
        recs = api.query_logger.tail()
        kinds = [(r["kind"], "error" in r) for r in recs]
        assert ("pql", False) in kinds and ("sql", False) in kinds
        assert ("pql", True) in kinds  # the failed parse is logged too
        assert all("duration_ms" in r for r in recs)
        assert any(r["query"] == "Count(Row(f=2))" for r in recs)
        # CaptureLogger captures module logs (reference: CaptureLogger)
        with CaptureLogger("mesh") as cap:
            from pilosa_tpu.obs.logger import get_logger

            get_logger("mesh").warning("hello %d", 7)
        assert cap.lines == ["hello 7"]
