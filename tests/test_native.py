"""Native host-kernel layer (native/pilosa_native.cpp via ctypes):
correctness vs the numpy fallbacks, and the fallback path itself.
The device path is XLA; these are the runtime's compiled host loops
(reference: roaring/roaring.go:711 popcounts, :2380 ImportRoaringBits)."""

import numpy as np
import pytest

from pilosa_tpu import native


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    W = 4096
    cols = rng.integers(0, W * 32, 50_000)
    return W, cols


def _np_scatter(plane, cols):
    np.bitwise_or.at(plane, cols >> 5,
                     np.uint32(1) << (cols & 31).astype(np.uint32))


def test_native_builds_and_matches_numpy(data):
    W, cols = data
    if not native.available():
        pytest.skip("no toolchain")
    p1 = np.zeros(W, dtype=np.uint32)
    p2 = np.zeros(W, dtype=np.uint32)
    native.scatter_bits(p1, cols)
    _np_scatter(p2, cols)
    assert (p1 == p2).all()
    assert native.popcount(p1) == int(np.unpackbits(p1.view(np.uint8)).sum())
    assert native.and_popcount(p1, p2) == native.popcount(p1)
    ref = np.nonzero(np.unpackbits(p1.view(np.uint8),
                                   bitorder="little"))[0]
    assert (native.plane_to_bits(p1) == ref).all()


def test_scatter_new_bits_counts_changed(data):
    W, cols = data
    p = np.zeros(W, dtype=np.uint32)
    ch = native.scatter_new_bits(p, cols)
    assert ch == native.popcount(p) == len(np.unique(cols))
    assert native.scatter_new_bits(p, cols) == 0  # idempotent


def test_popcount_never_value_casts():
    # uint64 input must be reinterpreted, not cast (a cast drops bits)
    x = np.array([1 << 40], dtype=np.uint64)
    assert native.popcount(x) == 1


def test_fallback_paths(monkeypatch, data):
    W, cols = data
    monkeypatch.setattr(native, "_load", lambda: None)
    p1 = np.zeros(W, dtype=np.uint32)
    native.scatter_bits(p1, cols)
    p2 = np.zeros(W, dtype=np.uint32)
    _np_scatter(p2, cols)
    assert (p1 == p2).all()
    q = np.zeros(W, dtype=np.uint32)
    assert native.scatter_new_bits(q, cols) == len(np.unique(cols))
    assert native.popcount(p1) == native.and_popcount(p1, p1)
    ref = np.nonzero(np.unpackbits(p1.view(np.uint8),
                                   bitorder="little"))[0]
    assert (native.plane_to_bits(p1) == ref).all()


def test_engine_consistent_with_and_without_native(tmp_path):
    # the same import through the fragment path must build identical
    # planes whichever backend ran
    from pilosa_tpu.core.fragment import SetFragment

    rng = np.random.default_rng(1)
    rows = rng.integers(0, 20, 30_000)
    cols = rng.integers(0, 1 << 20, 30_000)
    f1 = SetFragment(0)
    c1 = f1.set_many(rows, cols)
    lib = native._lib
    tried = native._tried
    try:
        native._lib, native._tried = None, True  # force fallback
        f2 = SetFragment(0)
        c2 = f2.set_many(rows, cols)
    finally:
        native._lib, native._tried = lib, tried
    assert c1 == c2
    assert (f1.planes[: len(f1.row_ids)] ==
            f2.planes[: len(f2.row_ids)]).all()


def test_gather_bits_both_backends(data):
    W, cols = data
    p = np.zeros(W, dtype=np.uint32)
    native.scatter_bits(p, cols)
    want = (((p[cols >> 5] >> (cols & 31).astype(np.uint32))
             & np.uint32(1))).astype(np.uint8)
    assert (native.gather_bits(p, cols) == want).all()
    lib, tried = native._lib, native._tried
    try:
        native._lib, native._tried = None, True
        assert (native.gather_bits(p, cols) == want).all()
    finally:
        native._lib, native._tried = lib, tried


def test_scatter_bounds_checked(data):
    W, _ = data
    p = np.zeros(W, dtype=np.uint32)
    for bad in ([-1], [W * 32]):
        import pytest as _pytest
        with _pytest.raises(IndexError):
            native.scatter_bits(p, np.array(bad))
        with _pytest.raises(IndexError):
            native.scatter_new_bits(p, np.array(bad))
