"""Cluster layer tests: placement math, distributed query/write/import
correctness against a single-node oracle, replica failover, state
gating (reference test model: executor_test.go over test.MustRunCluster,
internal/clustertests/pause_node_test.go)."""

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    ClusterSnapshot, ClusterStateError, InMemDisCo, LocalCluster, Node,
    STATE_DEGRADED, STATE_DOWN, STATE_NORMAL,
    jump_hash, key_to_partition, shard_to_partition,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH


def make_nodes(n):
    return [Node(id=f"node{i}", uri=f"http://host{i}") for i in range(n)]


class TestPlacement:
    def test_jump_hash_range_and_stability(self):
        for key in (0, 1, 7, 12345, 2**63):
            b = jump_hash(key, 7)
            assert 0 <= b < 7
            assert jump_hash(key, 7) == b

    def test_jump_hash_monotone_growth(self):
        # Adding a bucket only moves keys INTO the new bucket (the jump
        # hash invariant the reference relies on for minimal reshuffling).
        for key in range(200):
            before = jump_hash(key, 9)
            after = jump_hash(key, 10)
            assert after == before or after == 9

    def test_partitions_in_range(self):
        seen = set()
        for shard in range(512):
            p = shard_to_partition("i", shard)
            assert 0 <= p < 256
            seen.add(p)
        assert len(seen) > 200  # spread over most partitions

    def test_key_partition_differs_from_shard_partition_namespace(self):
        assert key_to_partition("i", "alice") == key_to_partition("i", "alice")
        assert key_to_partition("i", "alice") != key_to_partition("j", "alice") \
            or key_to_partition("i", "bob") != key_to_partition("j", "bob")

    def test_snapshot_replicas(self):
        snap = ClusterSnapshot(make_nodes(5), replica_n=3)
        owners = snap.shard_nodes("i", 42)
        assert len(owners) == 3
        assert len({n.id for n in owners}) == 3
        # consecutive around the sorted ring
        ids = [n.id for n in snap.nodes]
        i = ids.index(owners[0].id)
        assert [n.id for n in owners] == [ids[(i + r) % 5] for r in range(3)]

    def test_cluster_state_derivation(self):
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        ids = [n.id for n in snap.nodes]
        assert snap.cluster_state(ids) == STATE_NORMAL
        assert snap.cluster_state(ids[:2]) == STATE_DEGRADED
        assert snap.cluster_state(ids[:1]) == STATE_DOWN
        assert snap.cluster_state([]) == STATE_DOWN


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(3)
    yield c
    c.close()


def _fill(target, index="ci"):
    """Same data through any node/API surface."""
    target.create_index(index)
    target.create_field(index, "f")
    target.create_field(index, "n", {"type": "int"})
    rows, cols = [], []
    for c in range(0, 5 * SHARD_WIDTH, SHARD_WIDTH // 4):
        rows.append((c // 100) % 3)
        cols.append(c)
    target.import_bits(index, "f", rows=rows, cols=cols)
    vals_cols = list(range(0, 3 * SHARD_WIDTH, SHARD_WIDTH // 8))
    target.import_values(index, "n", cols=vals_cols,
                         values=[(i % 7) - 3 for i in range(len(vals_cols))])
    return index


class TestDistributedQueries:
    @pytest.fixture(scope="class")
    def filled(self, cluster):
        oracle = API()
        _fill(oracle)
        _fill(cluster.coordinator)
        return oracle

    @pytest.mark.parametrize("pql", [
        "Count(Row(f=0))",
        "Count(Union(Row(f=0), Row(f=1)))",
        "Count(Intersect(Row(f=0), Row(f=1)))",
        "Row(f=2)",
        "Sum(field=n)",
        "Min(field=n)",
        "Max(field=n)",
        "Sum(Row(f=0), field=n)",
        "TopN(f, n=2)",
        "Rows(f)",
        "GroupBy(Rows(f), limit=10)",
        "Count(Distinct(field=n))",
        "Percentile(field=n, nth=50)",
    ])
    def test_matches_single_node_oracle(self, cluster, filled, pql):
        want = filled.query("ci", pql)
        for node in cluster.nodes:  # any node can coordinate
            got = node.query("ci", pql)
            assert got == want, f"{pql} on {node.node.id}"

    def test_schema_visible_everywhere(self, cluster, filled):
        for node in cluster.nodes:
            assert "ci" in node.holder.indexes
            assert "f" in node.holder.index("ci").fields

    def test_data_is_actually_distributed(self, cluster, filled):
        # At least two nodes hold fragments (5 shards over 3 nodes).
        holders = sum(
            1 for node in cluster.nodes
            if node.holder.index("ci").shards())
        assert holders >= 2

    def test_writes_route_and_read_back(self, cluster, filled):
        cluster[1].query("ci", f"Set({7 * SHARD_WIDTH + 11}, f=9)")
        got = cluster[2].query("ci", "Row(f=9)")
        assert got[0].columns == [7 * SHARD_WIDTH + 11]
        assert filled.query("ci", "Count(Row(f=0))") == \
            cluster[0].query("ci", "Count(Row(f=0))")


class TestKeyedCluster:
    def test_keyed_set_and_query_across_nodes(self, cluster):
        co = cluster.coordinator
        co.create_index("ki", {"keys": True})
        co.create_field("ki", "color", {"keys": True})
        for person, color in [("alice", "red"), ("bob", "red"),
                              ("carol", "blue")]:
            co.query("ki", f'Set("{person}", color="{color}")')
        # Query from a different node: keys translate back.
        got = cluster[2].query("ki", 'Row(color="red")')
        assert sorted(got[0].keys) == ["alice", "bob"]
        top = cluster[1].query("ki", "TopN(color)")
        assert [(p.key, p.count) for p in top[0].pairs] == \
            [("red", 2), ("blue", 1)]
        # Unknown key reads empty, doesn't create.
        assert cluster[1].query("ki", 'Row(color="nope")')[0].columns == []

    def test_distinct_on_keyed_set_field(self, cluster):
        # Distinct over a set field returns ROW keys (field translator),
        # not record keys — regression for the index/field store mixup.
        got = cluster[1].query("ki", "Distinct(field=color)")
        assert sorted(got[0].keys) == ["blue", "red"]


class TestTranslateStoreConcurrency:
    def test_parallel_create_keys_unique_ids(self):
        from concurrent.futures import ThreadPoolExecutor
        from pilosa_tpu.core.translate import PartitionedTranslateStore

        store = PartitionedTranslateStore("i")

        def mk(t):
            return store.create_keys([f"k{t}-{j}" for j in range(500)])

        with ThreadPoolExecutor(max_workers=8) as pool:
            maps = list(pool.map(mk, range(8)))
        ids = [i for m in maps for i in m.values()]
        assert len(ids) == len(set(ids)) == 4000

    def test_load_over_foreign_journal_never_reuses_ids(self, tmp_path):
        # A journal with IDs dense in shard 0 (any older allocation
        # scheme) must not cause new allocations to collide.
        import json

        from pilosa_tpu.core.translate import PartitionedTranslateStore

        path = str(tmp_path / "keys.jsonl")
        with open(path, "w") as f:
            for i in range(50):
                f.write(json.dumps([f"old{i}", i]) + "\n")
        store = PartitionedTranslateStore("i", path)
        fresh = store.create_keys([f"new{i}" for i in range(50)])
        all_ids = set(range(50)) | set(fresh.values())
        assert len(all_ids) == 100  # no reuse
        assert store.translate_ids([3]) == {3: "old3"}


class TestFailover:
    def test_replica_failover_and_state_gating(self, tmp_path):
        c = LocalCluster(3, replica_n=2)
        try:
            co = c.coordinator
            _fill(co, index="fi")
            want = co.query("fi", "Count(Row(f=0))")[0]
            # Find a node that is NOT the coordinator and pause it.
            c.pause(1)
            assert co.state() in (STATE_DEGRADED,)
            # Reads still served via replicas.
            got = co.query("fi", "Count(Row(f=0))")[0]
            assert got == want
            # Writes refused while DEGRADED.
            with pytest.raises(ClusterStateError):
                co.query("fi", "Set(1, f=1)")
            with pytest.raises(ClusterStateError):
                co.create_index("nope")
            # Recovery restores NORMAL and writes.
            c.unpause(1)
            assert co.state() == STATE_NORMAL
            co.query("fi", "Set(1, f=1)")
        finally:
            c.close()

    def test_single_replica_down_is_down_for_missing_shards(self):
        c = LocalCluster(2, replica_n=1)
        try:
            co = c.coordinator
            _fill(co, index="si")
            c.pause(1)
            assert co.state() == STATE_DOWN
            with pytest.raises(ClusterStateError):
                co.query("si", "Count(Row(f=0))")
        finally:
            c.close()


class TestClusterTransactions:
    def test_exclusive_transaction_blocks_peer_writes(self):
        """Reference: server.go:1082 — transaction changes broadcast to
        peers so an exclusive transaction on node A blocks writes on node
        B (multi-node backup coordination)."""
        from pilosa_tpu.transaction import TransactionError

        c = LocalCluster(3)
        try:
            co = c.coordinator
            _fill(co, index="ti")
            tx = c[1].transactions.start(exclusive=True)
            assert tx.active  # alone -> immediately active
            # mirrored on every peer
            assert c[0].transactions.exclusive_active()
            assert c[2].transactions.exclusive_active()
            with pytest.raises(TransactionError):
                co.query("ti", "Set(99, f=1)")
            with pytest.raises(TransactionError):
                c[2].import_bits("ti", "f", rows=[1], cols=[99])
            # a peer can't start another transaction meanwhile
            with pytest.raises(TransactionError):
                c[0].transactions.start()
            # reads still work
            assert co.query("ti", "Count(Row(f=0))")[0] >= 0
            c[1].transactions.finish(tx.id)
            assert not c[0].transactions.exclusive_active()
            assert co.query("ti", "Set(99, f=1)") == [True]
        finally:
            c.close()


class TestClusterTimesMesh:
    """VERDICT r3 weak #6: the layering cluster/executor.py claims — HTTP
    reduce at the coordinator over per-node SPMD execution on the device
    mesh — exercised end-to-end in ONE test: a 3-node HTTP cluster whose
    nodes each run their local shards over the multi-device engine mesh,
    checked against a single-node oracle, with the mesh span asserted on
    the stacks the distributed query actually built."""

    def test_multinode_queries_run_on_multidevice_mesh(self):
        import jax

        from pilosa_tpu.parallel import mesh as meshmod

        n_dev = len(jax.devices())
        if n_dev < 2:
            pytest.skip("needs multiple (virtual) devices")
        meshmod.set_engine_mesh(meshmod.analytics_mesh(jax.devices()))
        c = LocalCluster(3)
        try:
            oracle = API()
            _fill(oracle, index="cm")
            _fill(c.coordinator, index="cm")
            for pql in ("Count(Row(f=0))", "TopN(f, n=3)", "Sum(field=n)",
                        "GroupBy(Rows(f), limit=10)"):
                want = oracle.query("cm", pql)
                got = c.coordinator.query("cm", pql)
                assert repr(got) == repr(want), pql
            # the distributed query's per-node stacks really spanned the
            # mesh: inspect every node's stacked cache
            spans = set()
            for node in c.nodes:
                idx = node.api.holder.indexes.get("cm")
                if idx is None:
                    continue
                for fld in idx.fields.values():
                    for inner in getattr(fld, "_stacked_cache", {}).values():
                        for _, st in inner.values():
                            for blk in getattr(st, "_blocks", []):
                                if blk is not None:
                                    spans.add(len(blk.sharding.device_set))
                            if not getattr(st, "paged", False) and hasattr(
                                    st, "planes"):
                                spans.add(len(st.planes.sharding.device_set))
            assert max(spans) == n_dev, (
                f"cluster-query stacks spanned {spans} devices, want {n_dev}")
        finally:
            c.close()
            meshmod.set_engine_mesh(None)


class TestSQLFanout:
    """Distributed SQL subtree execution (reference:
    sql3/planner/executionplanner.go:212-338 mapReducePlanOp /
    opfanout + wireprotocol.go). Host-filtered scans, JOIN build sides,
    and host aggregates execute on shard owners; only reduced streams
    cross the wire (VERDICT r4 missing #1)."""

    @pytest.fixture(scope="class")
    def sqldata(self, cluster):
        stmts = [
            "create table fs (_id id, seg id, v int)",
            "insert into fs values " + ",".join(
                f"({s * SHARD_WIDTH + i}, {(s + i) % 3}, {s * 10 + i})"
                for s in range(5) for i in range(8)),
            "create table fu (_id id, name string, age int)",
            "insert into fu values " + ",".join(
                f"({s * SHARD_WIDTH + i}, 'u{(s * 8 + i) % 4}', "
                f"{20 + (s * 8 + i) % 30})"
                for s in range(3) for i in range(8)),
            "create table fo (_id id, uid int, amt int)",
            "insert into fo values " + ",".join(
                f"({s * SHARD_WIDTH + i}, "
                f"{(s * 8 + i) * 7 % (5 * SHARD_WIDTH)}, {i + 1})"
                for s in range(4) for i in range(8)),
        ]
        oracle = API()
        for t in (cluster.coordinator, oracle):
            for stmt in stmts:
                t.sql(stmt)
        return oracle

    def _plan_ops(self, op):
        d = op.plan_json()
        out = []

        def walk(n):
            out.append(n["op"])
            for c in n.get("children", []):
                walk(c)
        walk(d)
        return out

    def test_host_filter_ships_with_subtree(self, cluster, sqldata):
        # v % 4 = 1 cannot lower to PQL -> must fan out, not pull
        sql = "select _id, v from fs where v % 4 = 1"
        from pilosa_tpu.sql import SQLEngine
        plan_ops = self._plan_ops(
            SQLEngine(cluster[1]).compile_plan(sql))
        assert "FanoutScanOp" in plan_ops, plan_ops
        got = cluster[1].sql(sql)
        want = sqldata.sql(sql)
        assert sorted(map(tuple, got.data)) == sorted(map(tuple, want.data))
        assert got.data  # non-degenerate

    def test_fanout_transfers_reduced_streams(self, cluster, sqldata):
        from pilosa_tpu.obs import metrics as M
        total_rows = sqldata.sql("select count(*) from fs").data[0][0]
        sel = "select _id from fs where v % 8 = 3"
        want = sqldata.sql(sel)
        before = M.REGISTRY.value(M.METRIC_SQL_FANOUT_ROWS)
        got = cluster.coordinator.sql(sel)
        shipped = M.REGISTRY.value(M.METRIC_SQL_FANOUT_ROWS) - before
        assert sorted(map(tuple, got.data)) == sorted(map(tuple, want.data))
        # only matching rows crossed the wire (remote share of matches),
        # strictly fewer than the table the coordinator used to pull
        assert 0 < shipped <= len(want.data) < total_rows

    def test_distributed_partial_aggregation(self, cluster, sqldata):
        sql = ("select seg, count(*), avg(v), min(v), max(v) from fs "
               "where v % 2 = 0 group by seg order by seg")
        from pilosa_tpu.sql import SQLEngine
        plan_ops = self._plan_ops(SQLEngine(cluster[2]).compile_plan(sql))
        assert "FanoutAggOp" in plan_ops, plan_ops
        got = cluster[2].sql(sql)
        want = sqldata.sql(sql)
        assert [list(r) for r in got.data] == [list(r) for r in want.data]

    def test_count_distinct_fanout(self, cluster, sqldata):
        sql = ("select count(distinct seg) from fs where v % 2 = 1")
        got = cluster.coordinator.sql(sql)
        want = sqldata.sql(sql)
        assert got.data == want.data

    def test_join_build_side_prefiltered(self, cluster, sqldata):
        # upper(name) can't lower: the users-side scan must fan out with
        # the host filter so the join build side arrives pre-filtered
        sql = ("select fu.name, sum(fo.amt) from fu "
               "inner join fo on fu._id = fo.uid "
               "where upper(fu.name) = 'U1' group by fu.name")
        from pilosa_tpu.sql import SQLEngine
        plan_ops = self._plan_ops(
            SQLEngine(cluster[1]).compile_plan(sql))
        assert "FanoutScanOp" in plan_ops, plan_ops
        got = cluster[1].sql(sql)
        want = sqldata.sql(sql)
        assert sorted(map(tuple, got.data)) == sorted(map(tuple, want.data))

    def test_fanout_survives_node_loss(self, cluster, sqldata):
        # data nodes die -> replicas (replica_n=1 here, so only the
        # coordinator-owned shards survive; the query must fail loudly,
        # not silently return partial data)
        sql = "select _id from fs where v % 4 = 1"
        cluster.pause(1)
        try:
            with pytest.raises(Exception):
                cluster.coordinator.sql(sql)
        finally:
            cluster.unpause(1)
        got = cluster.coordinator.sql(sql)
        want = sqldata.sql(sql)
        assert sorted(map(tuple, got.data)) == sorted(map(tuple, want.data))

    def test_order_limit_pushdown(self, cluster, sqldata):
        from pilosa_tpu.obs import metrics as M
        from pilosa_tpu.sql import SQLEngine
        from pilosa_tpu.sql.fanout import FanoutScanOp

        sql = ("select _id, v from fs where v % 2 = 1 "
               "order by v desc limit 3")
        plan_op = SQLEngine(cluster[1]).compile_plan(sql)

        def find_fanout(op):
            if isinstance(op, FanoutScanOp):
                return op
            for c in op.child_ops():
                f = find_fanout(c)
                if f is not None:
                    return f
            return None
        fo = find_fanout(plan_op)
        assert fo is not None and fo.spec.get("limit") == 3 \
            and fo.spec.get("order_by") == [["v", True]], fo and fo.spec
        before = M.REGISTRY.value(M.METRIC_SQL_FANOUT_ROWS)
        got = cluster[1].sql(sql)
        shipped = M.REGISTRY.value(M.METRIC_SQL_FANOUT_ROWS) - before
        want = sqldata.sql(sql)
        assert [list(r) for r in got.data] == [list(r) for r in want.data]
        # each remote node ships at most `limit` rows
        assert shipped <= 3 * (len(cluster) - 1)

    def test_order_limit_pushdown_alias_shadowing(self, cluster, sqldata):
        # `v % 4 as v` shadows the scan column: the coordinator sorts by
        # the projected expression, so the raw-column pushdown must NOT
        # happen (it would truncate the wrong rows per node)
        sql = ("select v % 4 as v from fs where v % 3 = 1 "
               "order by v desc limit 2")
        from pilosa_tpu.sql import SQLEngine
        from pilosa_tpu.sql.fanout import FanoutScanOp

        def find_fanout(op):
            if isinstance(op, FanoutScanOp):
                return op
            for c in op.child_ops():
                f = find_fanout(c)
                if f is not None:
                    return f
            return None
        fo = find_fanout(SQLEngine(cluster[1]).compile_plan(sql))
        assert fo is not None and "order_by" not in fo.spec
        got = cluster[1].sql(sql)
        want = sqldata.sql(sql)
        assert [list(r) for r in got.data] == [list(r) for r in want.data]


class TestLeaseDisCo:
    """Consensus-backed membership over a shared directory (reference:
    etcd/embed.go:458 lease heartbeats + watchNodes -> cluster state
    NORMAL/DEGRADED/DOWN, disco/disco.go:53-61). Dynamic join/leave must
    transition cluster state WITHOUT any node restarting (VERDICT r4
    missing #3)."""

    def _mk(self, tmp_path, ttl=0.6):
        from pilosa_tpu.cluster.disco import LeaseDisCo

        root = str(tmp_path / "disco")
        return lambda: LeaseDisCo(root, ttl=ttl, heartbeat_interval=0.1)

    def test_dynamic_join_visible_to_peers(self, tmp_path):
        import time

        from pilosa_tpu.cluster.node import ClusterNode
        from pilosa_tpu.server.http import serve

        factory = self._mk(tmp_path)
        c = LocalCluster(2, disco_factory=factory)
        try:
            c.coordinator.create_index("dj")
            c.coordinator.create_field("dj", "f")
            assert {n.id for n in c[0].disco.nodes()} == {"node0", "node1"}
            assert c[0].state() == "NORMAL"
            # a NEW node joins the running cluster — no restarts
            joiner = ClusterNode("node2", "", factory())
            srv, _ = serve(joiner, port=0, background=True)
            host, port = srv.server_address[:2]
            joiner.node.uri = f"http://{host}:{port}"
            joiner.disco.register(joiner.node)
            try:
                deadline = time.time() + 3
                while time.time() < deadline and \
                        len(c[0].disco.nodes()) != 3:
                    time.sleep(0.05)
                assert {n.id for n in c[0].disco.nodes()} == \
                    {"node0", "node1", "node2"}
                assert sorted(c[0].disco.live_ids()) == \
                    ["node0", "node1", "node2"]
                # writes now route to the joiner for shards it owns
                snap = c[0].snapshot()
                owners = {snap.shard_nodes("dj", s)[0].id
                          for s in range(12)}
                assert "node2" in owners
                # graceful leave: gone from membership, state stays NORMAL
                joiner.disco.leave()
                assert {n.id for n in c[0].disco.nodes()} == \
                    {"node0", "node1"}
                assert c[0].state() == "NORMAL"
            finally:
                srv.shutdown()
                srv.server_close()
        finally:
            c.close()

    def test_lease_expiry_degrades_then_recovers(self, tmp_path):
        import time

        factory = self._mk(tmp_path, ttl=0.5)
        c = LocalCluster(3, replica_n=2, disco_factory=factory)
        try:
            assert c[0].state() == "NORMAL"
            # crash node2 (no graceful leave): stop its heartbeat only
            c[2].disco._hb_stop.set()
            deadline = time.time() + 3
            while time.time() < deadline and \
                    "node2" in c[0].disco.live_ids():
                time.sleep(0.05)
            assert "node2" not in c[0].disco.live_ids()
            # still a member (lease expired, not removed) -> DEGRADED
            assert {n.id for n in c[0].disco.nodes()} == \
                {"node0", "node1", "node2"}
            assert c[0].state() == "DEGRADED"
            # heartbeat resumes -> NORMAL again, no restarts anywhere
            c[2].disco._hb_stop.clear()
            import threading
            t = threading.Thread(target=c[2].disco._keepalive, daemon=True)
            c[2].disco._hb_thread = t
            t.start()
            deadline = time.time() + 3
            while time.time() < deadline and c[0].state() != "NORMAL":
                time.sleep(0.05)
            assert c[0].state() == "NORMAL"
        finally:
            c.close()

    def test_mark_down_needs_fresh_heartbeat(self, tmp_path):
        import time

        from pilosa_tpu.cluster.disco import LeaseDisCo

        root = str(tmp_path / "d2")
        a = LeaseDisCo(root, ttl=5.0, heartbeat_interval=0.1)
        b = LeaseDisCo(root, ttl=5.0, heartbeat_interval=0.1)
        from pilosa_tpu.cluster.topology import Node
        a.register(Node(id="a", uri=""))
        b.register(Node(id="b", uri=""))
        try:
            assert sorted(a.live_ids()) == ["a", "b"]
            # transport failure: disbelieve b's current lease
            a.mark_down("b")
            assert a.live_ids() == ["a"]
            # a FRESH heartbeat from b restores it
            time.sleep(0.25)
            assert sorted(a.live_ids()) == ["a", "b"]
        finally:
            a.leave()
            b.leave()


class TestTranslateReplication:
    """Translate replication stream (reference: translate.go EntryReader
    + TranslationSyncer, http_translator.go; VERDICT r4 missing #7):
    owner-side creates push new (key, id) entries to partition replicas,
    and a promoted replica serves AND extends the namespace after the
    primary dies."""

    def test_replica_promoted_serves_keys(self, tmp_path):
        c = LocalCluster(3, replica_n=2)
        try:
            co = c.coordinator
            co.create_index("tk", {"keys": True})
            co.create_field("tk", "color", {"keys": True})
            # writes create record keys (partitioned) + row keys (field
            # primary); replication pushes entries to replicas
            co.import_bits("tk", "color",
                           row_keys=[f"c{i % 5}" for i in range(60)],
                           col_keys=[f"rec{i}" for i in range(60)])
            want = co.query("tk", "Count(Row(color=c1))")[0]
            assert want > 0
            # field-key primary is partition-0's primary; kill it
            snap = co.snapshot()
            primary = snap.partition_nodes(0)[0].id
            victim = int(primary.replace("node", ""))
            survivor = c[(victim + 1) % 3]
            c.pause(victim)
            # keys written BEFORE the kill resolve on the promoted
            # replica (post-snapshot entries arrived via the stream);
            # cluster is DEGRADED (reads only) with a node down
            got = survivor.query("tk", "Count(Row(color=c1))")[0]
            assert got == want
            # a promoted replica allocates NON-conflicting ids: its
            # store's allocator advanced past every replicated entry
            fstore = survivor.holder.index("tk").field("color").translate
            known = set(fstore.key_to_id.values())
            _, new = fstore.create_entries(["cNEW"])
            assert new and new[0][1] not in known
            # node returns: cluster NORMAL again, writes resume and the
            # replicated keys still resolve to the same rows everywhere
            c.unpause(victim)
            survivor.query("tk", 'Set("recNEW", color="cNEW2")')
            assert survivor.query("tk", "Count(Row(color=cNEW2))")[0] == 1
            assert survivor.query("tk", "Count(Row(color=c1))")[0] == want
        finally:
            c.close()

    def test_entries_identical_on_replicas(self):
        c = LocalCluster(3, replica_n=3)  # every node replicates all
        try:
            co = c.coordinator
            co.create_index("tr", {"keys": True})
            co.create_field("tr", "tag", {"keys": True})
            co.import_bits("tr", "tag",
                           row_keys=["a", "b", "a"],
                           col_keys=["x", "y", "z"])
            stores = [n.holder.index("tr").translate for n in c.nodes]
            maps = [dict(s.key_to_id) for s in stores]
            assert maps[0] and maps[0] == maps[1] == maps[2]
            fstores = [n.holder.index("tr").field("tag").translate
                       for n in c.nodes]
            fmaps = [dict(s.key_to_id) for s in fstores]
            assert fmaps[0] and fmaps[0] == fmaps[1] == fmaps[2]
        finally:
            c.close()


def test_mem_and_disk_usage_routes(tmp_path):
    import urllib.request

    from pilosa_tpu.server.http import serve

    api = API(str(tmp_path))
    api.create_index("u")
    api.create_field("u", "f")
    api.query("u", "Set(1, f=1)")
    api.save()
    srv, _ = serve(api, port=0, background=True)
    host, port = srv.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        import json as _json
        mem = _json.load(urllib.request.urlopen(base + "/internal/mem-usage"))
        assert mem["maxRSSBytes"] > 0 and mem["holderPlaneBytes"] > 0
        du = _json.load(urllib.request.urlopen(base + "/disk-usage"))
        assert du["usage"] > 0
        dui = _json.load(urllib.request.urlopen(base + "/disk-usage/u"))
        assert 0 < dui["usage"] <= du["usage"]
    finally:
        srv.shutdown()
        srv.server_close()
