"""Cluster layer tests: placement math, distributed query/write/import
correctness against a single-node oracle, replica failover, state
gating (reference test model: executor_test.go over test.MustRunCluster,
internal/clustertests/pause_node_test.go)."""

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    ClusterSnapshot, ClusterStateError, InMemDisCo, LocalCluster, Node,
    STATE_DEGRADED, STATE_DOWN, STATE_NORMAL,
    jump_hash, key_to_partition, shard_to_partition,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH


def make_nodes(n):
    return [Node(id=f"node{i}", uri=f"http://host{i}") for i in range(n)]


class TestPlacement:
    def test_jump_hash_range_and_stability(self):
        for key in (0, 1, 7, 12345, 2**63):
            b = jump_hash(key, 7)
            assert 0 <= b < 7
            assert jump_hash(key, 7) == b

    def test_jump_hash_monotone_growth(self):
        # Adding a bucket only moves keys INTO the new bucket (the jump
        # hash invariant the reference relies on for minimal reshuffling).
        for key in range(200):
            before = jump_hash(key, 9)
            after = jump_hash(key, 10)
            assert after == before or after == 9

    def test_partitions_in_range(self):
        seen = set()
        for shard in range(512):
            p = shard_to_partition("i", shard)
            assert 0 <= p < 256
            seen.add(p)
        assert len(seen) > 200  # spread over most partitions

    def test_key_partition_differs_from_shard_partition_namespace(self):
        assert key_to_partition("i", "alice") == key_to_partition("i", "alice")
        assert key_to_partition("i", "alice") != key_to_partition("j", "alice") \
            or key_to_partition("i", "bob") != key_to_partition("j", "bob")

    def test_snapshot_replicas(self):
        snap = ClusterSnapshot(make_nodes(5), replica_n=3)
        owners = snap.shard_nodes("i", 42)
        assert len(owners) == 3
        assert len({n.id for n in owners}) == 3
        # consecutive around the sorted ring
        ids = [n.id for n in snap.nodes]
        i = ids.index(owners[0].id)
        assert [n.id for n in owners] == [ids[(i + r) % 5] for r in range(3)]

    def test_cluster_state_derivation(self):
        snap = ClusterSnapshot(make_nodes(3), replica_n=2)
        ids = [n.id for n in snap.nodes]
        assert snap.cluster_state(ids) == STATE_NORMAL
        assert snap.cluster_state(ids[:2]) == STATE_DEGRADED
        assert snap.cluster_state(ids[:1]) == STATE_DOWN
        assert snap.cluster_state([]) == STATE_DOWN


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(3)
    yield c
    c.close()


def _fill(target, index="ci"):
    """Same data through any node/API surface."""
    target.create_index(index)
    target.create_field(index, "f")
    target.create_field(index, "n", {"type": "int"})
    rows, cols = [], []
    for c in range(0, 5 * SHARD_WIDTH, SHARD_WIDTH // 4):
        rows.append((c // 100) % 3)
        cols.append(c)
    target.import_bits(index, "f", rows=rows, cols=cols)
    vals_cols = list(range(0, 3 * SHARD_WIDTH, SHARD_WIDTH // 8))
    target.import_values(index, "n", cols=vals_cols,
                         values=[(i % 7) - 3 for i in range(len(vals_cols))])
    return index


class TestDistributedQueries:
    @pytest.fixture(scope="class")
    def filled(self, cluster):
        oracle = API()
        _fill(oracle)
        _fill(cluster.coordinator)
        return oracle

    @pytest.mark.parametrize("pql", [
        "Count(Row(f=0))",
        "Count(Union(Row(f=0), Row(f=1)))",
        "Count(Intersect(Row(f=0), Row(f=1)))",
        "Row(f=2)",
        "Sum(field=n)",
        "Min(field=n)",
        "Max(field=n)",
        "Sum(Row(f=0), field=n)",
        "TopN(f, n=2)",
        "Rows(f)",
        "GroupBy(Rows(f), limit=10)",
        "Count(Distinct(field=n))",
        "Percentile(field=n, nth=50)",
    ])
    def test_matches_single_node_oracle(self, cluster, filled, pql):
        want = filled.query("ci", pql)
        for node in cluster.nodes:  # any node can coordinate
            got = node.query("ci", pql)
            assert got == want, f"{pql} on {node.node.id}"

    def test_schema_visible_everywhere(self, cluster, filled):
        for node in cluster.nodes:
            assert "ci" in node.holder.indexes
            assert "f" in node.holder.index("ci").fields

    def test_data_is_actually_distributed(self, cluster, filled):
        # At least two nodes hold fragments (5 shards over 3 nodes).
        holders = sum(
            1 for node in cluster.nodes
            if node.holder.index("ci").shards())
        assert holders >= 2

    def test_writes_route_and_read_back(self, cluster, filled):
        cluster[1].query("ci", f"Set({7 * SHARD_WIDTH + 11}, f=9)")
        got = cluster[2].query("ci", "Row(f=9)")
        assert got[0].columns == [7 * SHARD_WIDTH + 11]
        assert filled.query("ci", "Count(Row(f=0))") == \
            cluster[0].query("ci", "Count(Row(f=0))")


class TestKeyedCluster:
    def test_keyed_set_and_query_across_nodes(self, cluster):
        co = cluster.coordinator
        co.create_index("ki", {"keys": True})
        co.create_field("ki", "color", {"keys": True})
        for person, color in [("alice", "red"), ("bob", "red"),
                              ("carol", "blue")]:
            co.query("ki", f'Set("{person}", color="{color}")')
        # Query from a different node: keys translate back.
        got = cluster[2].query("ki", 'Row(color="red")')
        assert sorted(got[0].keys) == ["alice", "bob"]
        top = cluster[1].query("ki", "TopN(color)")
        assert [(p.key, p.count) for p in top[0].pairs] == \
            [("red", 2), ("blue", 1)]
        # Unknown key reads empty, doesn't create.
        assert cluster[1].query("ki", 'Row(color="nope")')[0].columns == []

    def test_distinct_on_keyed_set_field(self, cluster):
        # Distinct over a set field returns ROW keys (field translator),
        # not record keys — regression for the index/field store mixup.
        got = cluster[1].query("ki", "Distinct(field=color)")
        assert sorted(got[0].keys) == ["blue", "red"]


class TestTranslateStoreConcurrency:
    def test_parallel_create_keys_unique_ids(self):
        from concurrent.futures import ThreadPoolExecutor
        from pilosa_tpu.core.translate import PartitionedTranslateStore

        store = PartitionedTranslateStore("i")

        def mk(t):
            return store.create_keys([f"k{t}-{j}" for j in range(500)])

        with ThreadPoolExecutor(max_workers=8) as pool:
            maps = list(pool.map(mk, range(8)))
        ids = [i for m in maps for i in m.values()]
        assert len(ids) == len(set(ids)) == 4000

    def test_load_over_foreign_journal_never_reuses_ids(self, tmp_path):
        # A journal with IDs dense in shard 0 (any older allocation
        # scheme) must not cause new allocations to collide.
        import json

        from pilosa_tpu.core.translate import PartitionedTranslateStore

        path = str(tmp_path / "keys.jsonl")
        with open(path, "w") as f:
            for i in range(50):
                f.write(json.dumps([f"old{i}", i]) + "\n")
        store = PartitionedTranslateStore("i", path)
        fresh = store.create_keys([f"new{i}" for i in range(50)])
        all_ids = set(range(50)) | set(fresh.values())
        assert len(all_ids) == 100  # no reuse
        assert store.translate_ids([3]) == {3: "old3"}


class TestFailover:
    def test_replica_failover_and_state_gating(self, tmp_path):
        c = LocalCluster(3, replica_n=2)
        try:
            co = c.coordinator
            _fill(co, index="fi")
            want = co.query("fi", "Count(Row(f=0))")[0]
            # Find a node that is NOT the coordinator and pause it.
            c.pause(1)
            assert co.state() in (STATE_DEGRADED,)
            # Reads still served via replicas.
            got = co.query("fi", "Count(Row(f=0))")[0]
            assert got == want
            # Writes refused while DEGRADED.
            with pytest.raises(ClusterStateError):
                co.query("fi", "Set(1, f=1)")
            with pytest.raises(ClusterStateError):
                co.create_index("nope")
            # Recovery restores NORMAL and writes.
            c.unpause(1)
            assert co.state() == STATE_NORMAL
            co.query("fi", "Set(1, f=1)")
        finally:
            c.close()

    def test_single_replica_down_is_down_for_missing_shards(self):
        c = LocalCluster(2, replica_n=1)
        try:
            co = c.coordinator
            _fill(co, index="si")
            c.pause(1)
            assert co.state() == STATE_DOWN
            with pytest.raises(ClusterStateError):
                co.query("si", "Count(Row(f=0))")
        finally:
            c.close()


class TestClusterTransactions:
    def test_exclusive_transaction_blocks_peer_writes(self):
        """Reference: server.go:1082 — transaction changes broadcast to
        peers so an exclusive transaction on node A blocks writes on node
        B (multi-node backup coordination)."""
        from pilosa_tpu.transaction import TransactionError

        c = LocalCluster(3)
        try:
            co = c.coordinator
            _fill(co, index="ti")
            tx = c[1].transactions.start(exclusive=True)
            assert tx.active  # alone -> immediately active
            # mirrored on every peer
            assert c[0].transactions.exclusive_active()
            assert c[2].transactions.exclusive_active()
            with pytest.raises(TransactionError):
                co.query("ti", "Set(99, f=1)")
            with pytest.raises(TransactionError):
                c[2].import_bits("ti", "f", rows=[1], cols=[99])
            # a peer can't start another transaction meanwhile
            with pytest.raises(TransactionError):
                c[0].transactions.start()
            # reads still work
            assert co.query("ti", "Count(Row(f=0))")[0] >= 0
            c[1].transactions.finish(tx.id)
            assert not c[0].transactions.exclusive_active()
            assert co.query("ti", "Set(99, f=1)") == [True]
        finally:
            c.close()


class TestClusterTimesMesh:
    """VERDICT r3 weak #6: the layering cluster/executor.py claims — HTTP
    reduce at the coordinator over per-node SPMD execution on the device
    mesh — exercised end-to-end in ONE test: a 3-node HTTP cluster whose
    nodes each run their local shards over the multi-device engine mesh,
    checked against a single-node oracle, with the mesh span asserted on
    the stacks the distributed query actually built."""

    def test_multinode_queries_run_on_multidevice_mesh(self):
        import jax

        from pilosa_tpu.parallel import mesh as meshmod

        n_dev = len(jax.devices())
        if n_dev < 2:
            pytest.skip("needs multiple (virtual) devices")
        meshmod.set_engine_mesh(meshmod.analytics_mesh(jax.devices()))
        c = LocalCluster(3)
        try:
            oracle = API()
            _fill(oracle, index="cm")
            _fill(c.coordinator, index="cm")
            for pql in ("Count(Row(f=0))", "TopN(f, n=3)", "Sum(field=n)",
                        "GroupBy(Rows(f), limit=10)"):
                want = oracle.query("cm", pql)
                got = c.coordinator.query("cm", pql)
                assert repr(got) == repr(want), pql
            # the distributed query's per-node stacks really spanned the
            # mesh: inspect every node's stacked cache
            spans = set()
            for node in c.nodes:
                idx = node.api.holder.indexes.get("cm")
                if idx is None:
                    continue
                for fld in idx.fields.values():
                    for inner in getattr(fld, "_stacked_cache", {}).values():
                        for _, st in inner.values():
                            for blk in getattr(st, "_blocks", []):
                                if blk is not None:
                                    spans.add(len(blk.sharding.device_set))
                            if not getattr(st, "paged", False) and hasattr(
                                    st, "planes"):
                                spans.add(len(st.planes.sharding.device_set))
            assert max(spans) == n_dev, (
                f"cluster-query stacks spanned {spans} devices, want {n_dev}")
        finally:
            c.close()
            meshmod.set_engine_mesh(None)
