"""Dataframe subsystem: expression compiler, store, Apply/Arrow PQL.

Reference analogs: apply.go/arrow.go behavior (dataframe_test.go,
arrow_test.go): changeset ingest per shard, Apply with a filter and a
program, Arrow extraction with a header, persistence.
"""

import numpy as np
import pytest

from pilosa_tpu.api import API
from pilosa_tpu.dataframe.expr import ExprError, compile_expr
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def api():
    a = API()
    a.create_index("t")
    a.create_field("t", "seg")
    return a


def fill(api, n=1000, shards=2):
    rng = np.random.default_rng(42)
    fares, dists = {}, {}
    for s in range(shards):
        ids = rng.choice(SHARD_WIDTH, size=n, replace=False)
        f = rng.uniform(1, 100, size=n).round(2)
        d = rng.integers(0, 50, size=n)
        api.import_dataframe("t", s, [int(i) for i in ids],
                             {"fare": [float(x) for x in f],
                              "dist": [int(x) for x in d]})
        for i, fa, di in zip(ids, f, d):
            g = s * SHARD_WIDTH + int(i)
            fares[g] = float(fa)
            dists[g] = int(di)
    return fares, dists


class TestExpr:
    def test_compile_and_eval(self):
        import jax.numpy as jnp

        fn, cols, red = compile_expr("sum(fare * 1.5 + 2)")
        assert cols == {"fare"} and red
        fare = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        mask = jnp.asarray([[True, False], [True, True]])
        got = float(fn({"fare": fare}, mask))
        assert got == pytest.approx((1 * 1.5 + 2) + (3 * 1.5 + 2) + (4 * 1.5 + 2))

    def test_reducers(self):
        import jax.numpy as jnp

        fare = jnp.asarray([[1.0, 5.0, 3.0]])
        mask = jnp.asarray([[True, True, False]])
        for src, want in [("min(fare)", 1.0), ("max(fare)", 5.0),
                          ("mean(fare)", 3.0), ("count(fare)", 2)]:
            fn, _, _ = compile_expr(src)
            assert float(fn({"fare": fare}, mask)) == pytest.approx(want)

    def test_vector_expr(self):
        import jax.numpy as jnp

        fn, _, red = compile_expr("fare / 2")
        assert not red
        out = fn({"fare": jnp.asarray([[4.0, 6.0]])},
                 jnp.asarray([[True, False]]))
        assert float(out[0, 0]) == 2.0 and np.isnan(np.asarray(out)[0, 1])

    def test_errors(self):
        with pytest.raises(ExprError):
            compile_expr("")
        with pytest.raises(ExprError):
            compile_expr("sum(")
        with pytest.raises(ExprError):
            compile_expr("bogusfn(x)")


class TestApply:
    def test_sum_matches_numpy(self, api):
        fares, _ = fill(api)
        got = api.query("t", 'Apply("sum(fare)")')[0]
        assert got.value == pytest.approx(sum(fares.values()), rel=1e-5)

    def test_filtered_aggregation(self, api):
        fares, _ = fill(api)
        chosen = sorted(fares)[:50]
        for c in chosen:
            api.query("t", f"Set({c}, seg=1)")
        got = api.query("t", 'Apply(Row(seg=1), "mean(fare)")')[0]
        want = np.mean([fares[c] for c in chosen])
        assert got.value == pytest.approx(want, rel=1e-5)

    def test_compound_expression(self, api):
        fares, dists = fill(api)
        got = api.query("t", 'Apply("sum(fare + dist * 2)")')[0]
        want = sum(fares[c] + dists[c] * 2 for c in fares if c in dists)
        assert got.value == pytest.approx(want, rel=1e-5)

    def test_vector_result(self, api):
        api.import_dataframe("t", 0, [5, 9], {"fare": [10.0, 20.0]})
        got = api.query("t", 'Apply("fare * 3")')[0]
        assert got.value == [30.0, 60.0]

    def test_count(self, api):
        fill(api, n=123, shards=1)
        got = api.query("t", 'Apply("count(fare)")')[0]
        assert got.value == 123

    def test_empty(self, api):
        got = api.query("t", 'Apply("sum(fare)")')[0]
        assert got.value == 0


class TestArrow:
    def test_extract_with_header(self, api):
        api.import_dataframe("t", 0, [3, 7], {"fare": [1.5, 2.5],
                                              "dist": [10, 20]})
        api.import_dataframe("t", 1, [0], {"fare": [9.0]})
        got = api.query("t", 'Arrow(header=["fare"])')[0]
        assert [f.name for f in got.fields] == ["fare"]
        assert got.ids == [3, 7, SHARD_WIDTH]
        assert got.columns == [[1.5, 2.5, 9.0]]

    def test_filtered_all_columns(self, api):
        api.import_dataframe("t", 0, [3, 7], {"fare": [1.5, 2.5],
                                              "dist": [10, 20]})
        api.query("t", "Set(7, seg=1)")
        got = api.query("t", "Arrow(Row(seg=1))")[0]
        assert got.ids == [7]
        by_name = dict(zip([f.name for f in got.fields], got.columns))
        assert by_name == {"fare": [2.5], "dist": [20]}


class TestDataframePersistence:
    def test_changeset_survives_crash(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("t")
        api.import_dataframe("t", 0, [1, 2], {"fare": [5.0, 6.0]})
        del api
        api2 = API(str(tmp_path))
        got = api2.query("t", 'Apply("sum(fare)")')[0]
        assert got.value == pytest.approx(11.0)

    def test_checkpoint_roundtrip(self, tmp_path):
        api = API(str(tmp_path))
        api.create_index("t")
        api.import_dataframe("t", 0, [1, 2], {"fare": [5.0, 6.0],
                                              "n": [1, 2]})
        api.save()
        assert api.holder.index("t").wal.record_bytes == 0
        del api
        api2 = API(str(tmp_path))
        assert api2.dataframe_schema("t") == [
            {"name": "fare", "type": "float64"},
            {"name": "n", "type": "int64"},
        ]
        got = api2.query("t", 'Apply("sum(fare + n)")')[0]
        assert got.value == pytest.approx(14.0)

    def test_http_endpoints(self, tmp_path):
        import json
        import urllib.request

        from pilosa_tpu.server.http import serve

        api = API()
        api.create_index("t")
        srv, _ = serve(api, port=0, background=True)
        port = srv.server_address[1]

        def req(method, path, body=None):
            r = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(body).encode() if body is not None else None,
                method=method)
            return json.loads(urllib.request.urlopen(r).read())

        assert req("POST", "/index/t/dataframe/0",
                   {"shard_ids": [1, 2], "columns": {"fare": [3.0, 4.0]}}
                   )["success"]
        assert req("GET", "/index/t/dataframe")["schema"] == [
            {"name": "fare", "type": "float64"}]
        got = req("GET", "/index/t/dataframe/0")
        assert got["columns"]["fare"]["positions"] == [1, 2]
        srv.shutdown()

    def test_http_apply_query(self):
        import json
        import urllib.request

        from pilosa_tpu.server.http import serve

        api = API()
        api.create_index("t")
        api.import_dataframe("t", 0, [1], {"fare": [2.5]})
        srv, _ = serve(api, port=0, background=True)
        port = srv.server_address[1]
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}/index/t/query",
            data='Apply("sum(fare)")'.encode(), method="POST")
        out = json.loads(urllib.request.urlopen(r).read())
        assert out["results"][0] == pytest.approx(2.5)
        srv.shutdown()
