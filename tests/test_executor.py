"""PQL end-to-end semantics tests.

The executable spec for the query engine — behaviors mirror the
reference's executor tests (executor_test.go / executor_internal_test.go):
every case sets data through PQL and checks query results, including
cross-shard behavior (columns beyond 2^20).
"""

import jax
import pytest

from pilosa_tpu.core import FieldOptions, FieldType, Holder, IndexOptions
from pilosa_tpu.parallel import mesh as meshmod
from pilosa_tpu.pql import Executor, parse
from pilosa_tpu.pql.executor import PQLError
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture(autouse=True, params=["1dev", "all"])
def engine_mesh(request):
    """The whole PQL spec must pass identically on a single device and on
    the full virtual mesh (VERDICT r1 #2: one code path for 1 and N)."""
    devices = jax.devices()
    if request.param == "1dev":
        meshmod.set_engine_mesh(meshmod.analytics_mesh(devices[:1]))
    else:
        meshmod.set_engine_mesh(meshmod.analytics_mesh(devices))
    yield
    meshmod.set_engine_mesh(None)


@pytest.fixture
def env():
    h = Holder()
    e = Executor(h)
    return h, e


def q(e, index, src):
    return e.execute(index, src)


class TestSetRowCount:
    def test_set_and_row(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        assert q(e, "i", "Set(10, f=1)") == [True]
        assert q(e, "i", "Set(10, f=1)") == [False]  # no change
        big = 3 * SHARD_WIDTH + 7
        assert q(e, "i", f"Set({big}, f=1)Set(11, f=2)") == [True, True]
        assert q(e, "i", "Row(f=1)")[0].columns == [10, big]
        assert q(e, "i", "Count(Row(f=1))") == [2]
        assert q(e, "i", "Count(Row(f=9))") == [0]

    def test_clear(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(10, f=1)Set(11, f=1)")
        assert q(e, "i", "Clear(10, f=1)") == [True]
        assert q(e, "i", "Clear(10, f=1)") == [False]
        assert q(e, "i", "Row(f=1)")[0].columns == [11]

    def test_boolean_algebra(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(1, f=1)Set(2, f=1)Set(3, f=1)Set(2, f=2)Set(3, f=2)Set(4, f=2)")
        assert q(e, "i", "Intersect(Row(f=1), Row(f=2))")[0].columns == [2, 3]
        assert q(e, "i", "Union(Row(f=1), Row(f=2))")[0].columns == [1, 2, 3, 4]
        assert q(e, "i", "Difference(Row(f=1), Row(f=2))")[0].columns == [1]
        assert q(e, "i", "Xor(Row(f=1), Row(f=2))")[0].columns == [1, 4]
        assert q(e, "i", "Count(Intersect(Row(f=1), Row(f=2)))") == [2]

    def test_not_all_existence(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(1, f=1)Set(2, f=1)Set(3, f=2)")
        assert q(e, "i", "All()")[0].columns == [1, 2, 3]
        assert q(e, "i", "Not(Row(f=1))")[0].columns == [3]
        assert q(e, "i", "Not(All())")[0].columns == []

    def test_cross_shard(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        cols = [5, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 5]
        for c in cols:
            q(e, "i", f"Set({c}, f=1)")
        assert q(e, "i", "Row(f=1)")[0].columns == cols
        assert q(e, "i", "Count(Row(f=1))") == [3]

    def test_shift_const_row(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(1, f=1)Set(5, f=1)")
        assert q(e, "i", "Shift(Row(f=1), n=2)")[0].columns == [3, 7]
        assert q(e, "i", "ConstRow(columns=[2, 9])")[0].columns == [2, 9]
        assert q(e, "i", "Intersect(Row(f=1), ConstRow(columns=[1]))")[0].columns == [1]

    def test_includes_column(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(10, f=1)")
        assert q(e, "i", "IncludesColumn(Row(f=1), column=10)") == [True]
        assert q(e, "i", "IncludesColumn(Row(f=1), column=11)") == [False]

    def test_limit_offset(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        for c in range(10):
            q(e, "i", f"Set({c}, f=1)")
        assert q(e, "i", "Limit(Row(f=1), limit=3)")[0].columns == [0, 1, 2]
        assert q(e, "i", "Limit(Row(f=1), limit=3, offset=4)")[0].columns == [4, 5, 6]

    def test_options_shards(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", f"Set(1, f=1)Set({SHARD_WIDTH + 2}, f=1)Set({2 * SHARD_WIDTH + 3}, f=1)")
        res = q(e, "i", "Options(Row(f=1), shards=[0, 2])")
        assert res[0].columns == [1, 2 * SHARD_WIDTH + 3]


class TestMutexBool:
    def test_mutex(self, env):
        h, e = env
        h.create_index("i").create_field("m", FieldOptions(type=FieldType.MUTEX))
        q(e, "i", "Set(1, m=10)Set(1, m=20)")
        assert q(e, "i", "Row(m=10)")[0].columns == []
        assert q(e, "i", "Row(m=20)")[0].columns == [1]

    def test_bool(self, env):
        h, e = env
        h.create_index("i").create_field("b", FieldOptions(type=FieldType.BOOL))
        q(e, "i", "Set(1, b=true)Set(2, b=false)Set(3, b=true)")
        assert q(e, "i", "Row(b=true)")[0].columns == [1, 3]
        assert q(e, "i", "Row(b=false)")[0].columns == [2]
        q(e, "i", "Set(1, b=false)")
        assert q(e, "i", "Row(b=true)")[0].columns == [3]


class TestBSI:
    def setup_data(self, e, h):
        idx = h.create_index("i")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        idx.create_field("f")
        data = {1: 3, 2: -7, 3: 100, SHARD_WIDTH + 1: 42, SHARD_WIDTH + 2: -7}
        for col, val in data.items():
            q(e, "i", f"Set({col}, n={val})")
        q(e, "i", "Set(1, f=1)Set(2, f=1)Set(3, f=1)")
        return data

    def test_row_conditions(self, env):
        h, e = env
        self.setup_data(e, h)
        assert q(e, "i", "Row(n > 0)")[0].columns == [1, 3, SHARD_WIDTH + 1]
        assert q(e, "i", "Row(n < 0)")[0].columns == [2, SHARD_WIDTH + 2]
        assert q(e, "i", "Row(n == -7)")[0].columns == [2, SHARD_WIDTH + 2]
        assert q(e, "i", "Row(n != -7)")[0].columns == [1, 3, SHARD_WIDTH + 1]
        assert q(e, "i", "Row(n >= 42)")[0].columns == [3, SHARD_WIDTH + 1]
        assert q(e, "i", "Row(-10 < n < 50)")[0].columns == [1, 2, SHARD_WIDTH + 1, SHARD_WIDTH + 2]
        assert q(e, "i", "Row(n != null)")[0].columns == sorted(
            [1, 2, 3, SHARD_WIDTH + 1, SHARD_WIDTH + 2])

    def test_sum_min_max(self, env):
        h, e = env
        data = self.setup_data(e, h)
        r = q(e, "i", "Sum(field=n)")[0]
        assert (r.val, r.count) == (sum(data.values()), 5)
        r = q(e, "i", "Sum(Row(f=1), field=n)")[0]
        assert (r.val, r.count) == (3 - 7 + 100, 3)
        r = q(e, "i", "Min(field=n)")[0]
        assert (r.val, r.count) == (-7, 2)
        r = q(e, "i", "Max(field=n)")[0]
        assert (r.val, r.count) == (100, 1)
        r = q(e, "i", "Min(Row(f=1), field=n)")[0]
        assert (r.val, r.count) == (-7, 1)

    def test_overwrite_and_clear(self, env):
        h, e = env
        self.setup_data(e, h)
        q(e, "i", "Set(3, n=5)")  # overwrite 100 -> 5
        assert q(e, "i", "Max(field=n)")[0].val == 42
        q(e, "i", "Clear(3, n=5)")
        assert q(e, "i", "Row(n != null)")[0].columns == [1, 2, SHARD_WIDTH + 1, SHARD_WIDTH + 2]

    def test_distinct(self, env):
        h, e = env
        self.setup_data(e, h)
        assert q(e, "i", "Distinct(field=n)") == [[-7, 3, 42, 100]]
        assert q(e, "i", "Count(Distinct(field=n))") == [4]

    def test_percentile(self, env):
        h, e = env
        idx = h.create_index("p")
        idx.create_field("v", FieldOptions(type=FieldType.INT))
        vals = [10, 20, 30, 40, 50, 60, 70, 80, 90, 100]
        for i, v in enumerate(vals):
            q(e, "p", f"Set({i}, v={v})")
        assert q(e, "p", "Percentile(field=v, nth=50)")[0].val == 50
        assert q(e, "p", "Percentile(field=v, nth=0)")[0].val == 10
        assert q(e, "p", "Percentile(field=v, nth=100)")[0].val == 100

    def test_decimal(self, env):
        h, e = env
        idx = h.create_index("d")
        idx.create_field("price", FieldOptions(type=FieldType.DECIMAL, scale=2))
        q(e, "d", "Set(1, price=10.50)Set(2, price=0.25)")
        r = q(e, "d", "Sum(field=price)")[0]
        assert r.val == pytest.approx(10.75)
        assert q(e, "d", "Row(price > 1.0)")[0].columns == [1]


class TestTopNRows:
    def setup_data(self, e, h):
        h.create_index("i").create_field("f")
        # row 1: 4 cols, row 2: 2 cols, row 3: 1 col, spread over 2 shards
        for c in (1, 2, 3, SHARD_WIDTH + 1):
            q(e, "i", f"Set({c}, f=1)")
        for c in (1, SHARD_WIDTH + 2):
            q(e, "i", f"Set({c}, f=2)")
        q(e, "i", "Set(9, f=3)")

    def test_topn(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "i", "TopN(f, n=2)")[0]
        assert [(p.id, p.count) for p in r.pairs] == [(1, 4), (2, 2)]
        r = q(e, "i", "TopN(f)")[0]
        assert [(p.id, p.count) for p in r.pairs] == [(1, 4), (2, 2), (3, 1)]
        r = q(e, "i", "TopK(f, k=1)")[0]
        assert [(p.id, p.count) for p in r.pairs] == [(1, 4)]

    def test_topn_with_filter(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "i", "TopN(f, Row(f=2), n=5)")[0]
        assert [(p.id, p.count) for p in r.pairs] == [(1, 1), (2, 2)][::-1] or True
        # filter = Row(f=2) has cols {1, S+2}: row1∩ = {1}, row2∩ = both
        assert {(p.id, p.count) for p in r.pairs} == {(2, 2), (1, 1)}

    def test_rows(self, env):
        h, e = env
        self.setup_data(e, h)
        assert q(e, "i", "Rows(f)") == [[1, 2, 3]]
        assert q(e, "i", "Rows(f, limit=2)") == [[1, 2]]
        assert q(e, "i", "Rows(f, previous=1)") == [[2, 3]]
        assert q(e, "i", "Rows(f, column=9)") == [[3]]
        assert q(e, "i", "Rows(f, column=1)") == [[1, 2]]

    def test_union_rows(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "i", "UnionRows(Rows(f))")[0]
        assert r.columns == [1, 2, 3, 9, SHARD_WIDTH + 1, SHARD_WIDTH + 2]


class TestGroupBy:
    def setup_data(self, e, h):
        idx = h.create_index("i")
        idx.create_field("a")
        idx.create_field("b")
        idx.create_field("v", FieldOptions(type=FieldType.INT))
        # a=1: cols 1,2,3 ; a=2: cols 4,5
        # b=10: cols 1,2,4 ; b=20: cols 3,5
        for c in (1, 2, 3):
            q(e, "i", f"Set({c}, a=1)")
        for c in (4, 5):
            q(e, "i", f"Set({c}, a=2)")
        for c in (1, 2, 4):
            q(e, "i", f"Set({c}, b=10)")
        for c in (3, 5):
            q(e, "i", f"Set({c}, b=20)")
        for c, v in [(1, 100), (2, 10), (3, 1), (4, 5), (5, 7)]:
            q(e, "i", f"Set({c}, v={v})")

    def expect_counts(self, res):
        return {tuple((g.field, g.row_id) for g in gc.group): gc.count for gc in res}

    def test_single_field(self, env):
        h, e = env
        self.setup_data(e, h)
        res = q(e, "i", "GroupBy(Rows(a))")[0]
        assert self.expect_counts(res) == {(("a", 1),): 3, (("a", 2),): 2}

    def test_two_fields(self, env):
        h, e = env
        self.setup_data(e, h)
        res = q(e, "i", "GroupBy(Rows(a), Rows(b))")[0]
        assert self.expect_counts(res) == {
            (("a", 1), ("b", 10)): 2,
            (("a", 1), ("b", 20)): 1,
            (("a", 2), ("b", 10)): 1,
            (("a", 2), ("b", 20)): 1,
        }

    def test_filter(self, env):
        h, e = env
        self.setup_data(e, h)
        res = q(e, "i", "GroupBy(Rows(a), filter=Row(b=10))")[0]
        assert self.expect_counts(res) == {(("a", 1),): 2, (("a", 2),): 1}

    def test_aggregate_sum(self, env):
        h, e = env
        self.setup_data(e, h)
        res = q(e, "i", "GroupBy(Rows(a), aggregate=Sum(field=v))")[0]
        by_key = {tuple((g.field, g.row_id) for g in gc.group): gc.agg for gc in res}
        assert by_key == {(("a", 1),): 111, (("a", 2),): 12}

    def test_three_fields(self, env):
        h, e = env
        self.setup_data(e, h)
        idx = h.index("i")
        idx.create_field("c")
        q(e, "i", "Set(1, c=7)Set(3, c=7)Set(5, c=8)")
        res = q(e, "i", "GroupBy(Rows(a), Rows(b), Rows(c))")[0]
        assert self.expect_counts(res) == {
            (("a", 1), ("b", 10), ("c", 7)): 1,
            (("a", 1), ("b", 20), ("c", 7)): 1,
            (("a", 2), ("b", 20), ("c", 8)): 1,
        }

    def test_limit(self, env):
        h, e = env
        self.setup_data(e, h)
        res = q(e, "i", "GroupBy(Rows(a), Rows(b), limit=2)")[0]
        assert len(res) == 2


class TestKeys:
    def test_column_and_row_keys(self, env):
        h, e = env
        idx = h.create_index("users", IndexOptions(keys=True))
        idx.create_field("likes", FieldOptions(keys=True))
        q(e, "users", 'Set("alice", likes="pizza")')
        q(e, "users", 'Set("bob", likes="pizza")')
        q(e, "users", 'Set("alice", likes="sushi")')
        r = q(e, "users", 'Row(likes="pizza")')[0]
        assert r.keys == ["alice", "bob"]
        assert q(e, "users", 'Count(Row(likes="sushi"))') == [1]
        # unknown key reads as empty
        assert q(e, "users", 'Row(likes="nope")')[0].keys == []
        r = q(e, "users", "TopN(likes)")[0]
        assert [(p.key, p.count) for p in r.pairs] == [("pizza", 2), ("sushi", 1)]
        assert q(e, "users", "Rows(likes)") == [["pizza", "sushi"]]

    def test_store(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", "Set(1, f=1)Set(2, f=1)Set(2, f=2)")
        assert q(e, "i", "Store(Intersect(Row(f=1), Row(f=2)), f=9)") == [True]
        assert q(e, "i", "Row(f=9)")[0].columns == [2]

    def test_clear_row(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        q(e, "i", f"Set(1, f=1)Set({SHARD_WIDTH + 1}, f=1)")
        assert q(e, "i", "ClearRow(f=1)") == [True]
        assert q(e, "i", "Row(f=1)")[0].columns == []


class TestTimeRanges:
    def test_row_time_range(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("t", FieldOptions(type=FieldType.TIME, time_quantum="YMDH"))
        q(e, "i", "Set(1, t=1, 2010-01-01T00:00)")
        q(e, "i", "Set(2, t=1, 2010-06-15T12:00)")
        q(e, "i", "Set(3, t=1, 2011-01-01T00:00)")
        r = q(e, "i", "Row(t=1, from='2010-01-01T00:00', to='2011-01-01T00:00')")[0]
        assert r.columns == [1, 2]
        r = q(e, "i", "Row(t=1, from='2010-06-01T00:00', to='2010-07-01T00:00')")[0]
        assert r.columns == [2]
        # No range: standard view has everything.
        assert q(e, "i", "Row(t=1)")[0].columns == [1, 2, 3]

    def test_topn_time_range(self, env):
        """TopN(from, to) must count only the covering quantum views, not
        the standard view (VERDICT r1-r3 carry-over)."""
        h, e = env
        idx = h.create_index("i")
        idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                           time_quantum="YMDH"))
        # row 1: 3 columns in 2010, 1 in 2011; row 2: 1 in 2010, 2 in 2011
        q(e, "i", "Set(1, t=1, 2010-02-01T00:00)")
        q(e, "i", "Set(2, t=1, 2010-03-01T00:00)")
        q(e, "i", f"Set({SHARD_WIDTH + 5}, t=1, 2010-04-01T00:00)")
        q(e, "i", "Set(9, t=1, 2011-05-01T00:00)")
        q(e, "i", "Set(3, t=2, 2010-02-01T00:00)")
        q(e, "i", "Set(4, t=2, 2011-03-01T00:00)")
        q(e, "i", "Set(5, t=2, 2011-04-01T00:00)")
        # per-view oracle for the 2010 range
        pairs = q(e, "i",
                  "TopN(t, from='2010-01-01T00:00', to='2011-01-01T00:00')"
                  )[0].pairs
        assert [(p.id, p.count) for p in pairs] == [(1, 3), (2, 1)]
        # 2011 flips the ranking
        pairs = q(e, "i",
                  "TopN(t, from='2011-01-01T00:00', to='2012-01-01T00:00')"
                  )[0].pairs
        assert [(p.id, p.count) for p in pairs] == [(2, 2), (1, 1)]
        # no range: standard view counts everything
        pairs = q(e, "i", "TopN(t)")[0].pairs
        assert [(p.id, p.count) for p in pairs] == [(1, 4), (2, 3)]
        # sub-range covering multiple finer views within one year
        pairs = q(e, "i",
                  "TopN(t, from='2010-02-01T00:00', to='2010-04-01T00:00')"
                  )[0].pairs
        assert [(p.id, p.count) for p in pairs] == [(1, 2), (2, 1)]

    def test_rows_time_range(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                           time_quantum="YMD"))
        q(e, "i", "Set(1, t=1, 2010-02-01T00:00)")
        q(e, "i", "Set(2, t=2, 2011-03-01T00:00)")
        assert q(e, "i",
                 "Rows(t, from='2010-01-01T00:00', to='2011-01-01T00:00')"
                 )[0] == [1]
        assert q(e, "i", "Rows(t)")[0] == [1, 2]


class TestExtract:
    def test_extract(self, env):
        h, e = env
        idx = h.create_index("i")
        idx.create_field("s")
        idx.create_field("n", FieldOptions(type=FieldType.INT))
        q(e, "i", "Set(1, s=10)Set(1, s=20)Set(2, s=10)")
        q(e, "i", "Set(1, n=-5)")
        t = q(e, "i", "Extract(All(), Rows(s), Rows(n))")[0]
        assert [f.name for f in t.fields] == ["s", "n"]
        by_col = {c.column: c.rows for c in t.columns}
        assert by_col == {1: [[10, 20], -5], 2: [[10], None]}


class TestErrors:
    def test_unknown_field(self, env):
        h, e = env
        h.create_index("i")
        with pytest.raises(KeyError):
            q(e, "i", "Row(nope=1)")

    def test_unknown_call(self, env):
        h, e = env
        h.create_index("i")
        with pytest.raises(PQLError):
            q(e, "i", "Frobnicate(x=1)")

    def test_parse_error(self, env):
        h, e = env
        h.create_index("i")
        with pytest.raises(ValueError):
            q(e, "i", "Row(f=")

    def test_string_key_on_unkeyed(self, env):
        h, e = env
        h.create_index("i").create_field("f")
        with pytest.raises(PQLError):
            q(e, "i", 'Set(1, f="key")')


class TestBatchedRegressions:
    """Regressions for the stacked/batched execution layer."""

    def test_percentile_large_total_no_overflow(self, env):
        # rank = ceil(nth/100 * total) must not wrap int32 when computed on
        # device: 250k values at nth=100 overflows a naive
        # nth_x100 * total product (ops/bsi.py _kth_kernel).
        h, e = env
        idx = h.create_index("p")
        f = idx.create_field("v", FieldOptions(type=FieldType.INT))
        n = 250_000
        cols = list(range(n))
        f.set_values(cols, [1] * (n - 1) + [5])
        for c in cols:
            idx.add_exists(c)
        assert q(e, "p", "Percentile(field=v, nth=100)")[0].val == 5
        assert q(e, "p", "Percentile(field=v, nth=50)")[0].val == 1

    def test_groupby_sum_fold_matches_dense(self, env, monkeypatch):
        # High-cardinality 2-field GroupBy+Sum falls back to the pruning
        # fold path; its results must match the dense MXU path.
        h, e = env
        idx = h.create_index("g")
        idx.create_field("a")
        idx.create_field("b")
        idx.create_field("v", FieldOptions(type=FieldType.INT))
        pql = ("Set(1, a=1)Set(2, a=1)Set(3, a=2)Set(1, b=10)Set(3, b=10)"
               "Set(2, b=20)Set(1, v=7)Set(2, v=-3)Set(3, v=100)")
        q(e, "g", pql)
        query = "GroupBy(Rows(a), Rows(b), aggregate=Sum(field=v))"
        dense = q(e, "g", query)[0]
        monkeypatch.setattr(Executor, "_groupby_dense_ok",
                            staticmethod(lambda sts, agg_st: False))
        fold = q(e, "g", query)[0]
        assert dense == fold
        by_key = {tuple((g.field, g.row_id) for g in gc.group):
                  (gc.count, gc.agg) for gc in dense}
        assert by_key == {
            (("a", 1), ("b", 10)): (1, 7),
            (("a", 1), ("b", 20)): (1, -3),
            (("a", 2), ("b", 10)): (1, 100),
        }


class TestSortFieldValue:
    def setup_data(self, e, h):
        idx = h.create_index("s")
        idx.create_field("v", FieldOptions(type=FieldType.INT))
        idx.create_field("b", FieldOptions(type=FieldType.BOOL))
        idx.create_field("f")
        q(e, "s", "Set(1, v=30)Set(2, v=10)Set(3, v=20)Set(1, f=1)Set(3, f=1)")
        q(e, "s", "Set(1, b=true)Set(2, b=false)")

    def test_sort_asc_desc(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "s", "Sort(field=v)")[0]
        assert r.columns == [2, 3, 1] and r.values == [10, 20, 30]
        r = q(e, "s", "Sort(field=v, sort-desc=true)")[0]
        assert r.columns == [1, 3, 2]

    def test_sort_filtered_limit(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "s", "Sort(Row(f=1), field=v, limit=1)")[0]
        assert r.columns == [3] and r.values == [20]

    def test_sort_bool(self, env):
        h, e = env
        self.setup_data(e, h)
        r = q(e, "s", "Sort(field=b)")[0]
        assert r.columns == [2, 1] and r.values == [False, True]

    def test_sort_cross_shard(self, env):
        h, e = env
        self.setup_data(e, h)
        big = SHARD_WIDTH + 9
        q(e, "s", f"Set({big}, v=15)")
        r = q(e, "s", "Sort(field=v)")[0]
        assert r.columns == [2, big, 3, 1]

    def test_field_value(self, env):
        h, e = env
        self.setup_data(e, h)
        assert q(e, "s", "FieldValue(field=v, column=3)")[0].val == 20
        assert q(e, "s", "FieldValue(field=v, column=99)")[0].count == 0
        assert q(e, "s", "FieldValue(field=b, column=1)")[0].val is True
        assert q(e, "s", "FieldValue(field=b, column=2)")[0].val is False

    def test_external_lookup_unconfigured(self, env):
        h, e = env
        h.create_index("s").create_field("f")
        with pytest.raises(PQLError):
            q(e, "s", 'ExternalLookup(query="select 1")')

    def test_external_lookup_plugged(self, env):
        h, e = env
        h.create_index("s")
        e.external_lookup = lambda query, write: {"echo": query}
        assert q(e, "s", 'ExternalLookup(query="x")')[0] == {"echo": "x"}
