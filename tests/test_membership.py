"""SWIM membership tests: merged-view precedence, incarnation-numbered
refutation, indirect probing, suspect-timeout confirmation, the
GossipDisCo liveness swap, FaultPlan network partitions, the translate
outbox race regression, and 3-node partition chaos (clean split,
asymmetric ping-only cut, coordinator drop) with bit-identical
convergence against a single-node oracle after heal.

scripts/tier1.sh re-runs this file under two fixed values of
PILOSA_TPU_FAULT_SEED — every test must hold for ANY seed: partition
rules here are deterministic cuts (no ``prob``), and tests that pin
exact probe sequences construct their plans and agents with explicit
seeds."""

import json
import threading
import types
import urllib.request

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.cluster import (
    FaultPlan, GossipDisCo, GossipState, InjectedFault, InMemDisCo,
    LocalCluster, Node, NodeDownError,
)
from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.topology import ClusterSnapshot
from pilosa_tpu.cluster.translator import ClusterTranslator
from pilosa_tpu.gossip import (
    KIND_CONTROL, KIND_MEMBER, KIND_TRANSLATE,
    MEMBER_ALIVE, MEMBER_DOWN, MEMBER_SUSPECT, Membership,
)
from pilosa_tpu.gossip.membership import PingToken
from pilosa_tpu.obs import metrics as M
from pilosa_tpu.obs.metrics import MetricsRegistry
from pilosa_tpu.sched import ManualClock
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _mknodes(n):
    return [Node(id=f"node{i}", uri="") for i in range(n)]


class _ScriptClient:
    """Scripted membership_ping transport for protocol unit tests.

    ``ok[target_id]`` scripts the direct ping to that node;
    ``ok[("via", relay_id, target_id)]`` scripts a relay's onward probe
    (falls back to ``ok[target_id]``). Unlisted targets answer ok."""

    def __init__(self, ok=None):
        self.ok = dict(ok or {})
        self.calls = []

    def membership_ping(self, node, payload, token=None):
        target = payload.get("target")
        if target is not None:  # ping-req relay
            tid = target["id"]
            self.calls.append(("relay", node.id, tid))
            good = self.ok.get(("via", node.id, tid), self.ok.get(tid, True))
            return {"ok": bool(good), "relay": node.id}
        self.calls.append(("ping", node.id))
        if self.ok.get(node.id, True):
            return {"ok": True, "node": node.id}
        raise NodeDownError(f"scripted down: {node.id}")


def _mkmember(node_id="node0", n=3, ok=None, clock=None, seed=7, **kw):
    clock = clock or ManualClock()
    nodes = _mknodes(n)
    reg = MetricsRegistry()
    agent = types.SimpleNamespace(
        state=GossipState(node_id, clock=clock, registry=reg),
        seed=seed, clock=clock, registry=reg)
    client = _ScriptClient(ok)
    peers = [x for x in nodes if x.id != node_id]
    m = Membership(node_id, agent, client, lambda: peers, **kw)
    return m, agent, client, clock


def _remote(node_id, clock=None):
    """A peer's GossipState to author remote member records with."""
    return GossipState(node_id, clock=clock or ManualClock(),
                       registry=MetricsRegistry())


class TestPingToken:
    def test_never_cancels(self):
        tok = PingToken(0.05)
        assert tok.cancelled is False
        assert tok.timeout_s == 0.05
        assert tok.wait(0.0) is False


class TestMergedView:
    def test_bootstrap_defaults_alive(self):
        m, *_ = _mkmember()
        view = m.view()
        assert set(view) == {"node0", "node1", "node2"}
        assert all(r["status"] == MEMBER_ALIVE for r in view.values())
        assert view["node0"]["incarnation"] == 1  # self at own inc

    def test_suspect_outranks_alive_at_same_incarnation(self):
        m, agent, *_ = _mkmember("node0")
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node2"), [MEMBER_SUSPECT, 1])
        agent.state.apply(other.deltas_since({}))
        # our own alive@1 assertion cannot clear node1's suspicion
        m.evidence_alive("node2")
        assert m.status_of("node2") == MEMBER_SUSPECT

    def test_alive_at_higher_incarnation_refutes(self):
        m, agent, *_ = _mkmember("node0")
        a, b = _remote("node1"), _remote("node2")
        a.bump_local((KIND_MEMBER, "node2"), [MEMBER_DOWN, 1])
        b.bump_local((KIND_MEMBER, "node2"), [MEMBER_ALIVE, 2])
        agent.state.apply(a.deltas_since({}) + b.deltas_since({}))
        assert m.status_of("node2") == MEMBER_ALIVE
        assert m.view()["node2"]["incarnation"] == 2

    def test_down_outranks_suspect(self):
        m, agent, *_ = _mkmember("node0")
        a, b = _remote("node1"), _remote("node2")
        a.bump_local((KIND_MEMBER, "node1"), [MEMBER_SUSPECT, 3])
        b.bump_local((KIND_MEMBER, "node1"), [MEMBER_DOWN, 3])
        agent.state.apply(a.deltas_since({}) + b.deltas_since({}))
        assert m.status_of("node1") == MEMBER_DOWN

    def test_malformed_records_ignored(self):
        m, agent, *_ = _mkmember("node0")
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node2"), ["zombie", 1])
        other.bump_local((KIND_MEMBER, "node1"), "not-a-record")
        agent.state.apply(other.deltas_since({}))
        assert m.status_of("node2") == MEMBER_ALIVE
        assert m.status_of("node1") == MEMBER_ALIVE

    def test_live_ids_excludes_only_confirmed_down(self):
        m, agent, *_ = _mkmember("node0")
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node1"), [MEMBER_SUSPECT, 1])
        other.bump_local((KIND_MEMBER, "node2"), [MEMBER_DOWN, 1])
        agent.state.apply(other.deltas_since({}))
        ids = ["node0", "node1", "node2"]
        assert m.live_ids(ids) == ["node0", "node1"]  # suspects routed


class TestRefutation:
    def test_gossiped_suspicion_triggers_incarnation_bump(self):
        m, agent, *_ = _mkmember("node0")
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node0"), [MEMBER_SUSPECT, 1])
        agent.state.apply(other.deltas_since({}))
        assert m.incarnation == 2
        view = m.view()
        assert view["node0"] == {"status": MEMBER_ALIVE, "incarnation": 2}
        assert m.registry.value(M.METRIC_MEMBERSHIP_REFUTATIONS,
                                node="node0") == 1.0

    def test_confirmed_down_refuted_the_same_way(self):
        m, agent, *_ = _mkmember("node0")
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node0"), [MEMBER_DOWN, 4])
        agent.state.apply(other.deltas_since({}))
        assert m.incarnation == 5
        assert m.status_of("node0") == MEMBER_ALIVE

    def test_stale_suspicion_is_ignored(self):
        m, agent, *_ = _mkmember("node0")
        m.refute(3)  # incarnation -> 4
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node0"), [MEMBER_SUSPECT, 2])
        agent.state.apply(other.deltas_since({}))
        assert m.incarnation == 4  # alive@4 already outranks suspect@2
        assert m.status_of("node0") == MEMBER_ALIVE

    def test_tick_self_refutes_without_the_listener(self):
        # the apply-path listener normally refutes instantly; the tick
        # must also catch it (e.g. records merged while disabled)
        m, agent, *_ = _mkmember("node0")
        agent.state.remove_kind_listener(KIND_MEMBER, m._on_member_entry)
        other = _remote("node1")
        other.bump_local((KIND_MEMBER, "node0"), [MEMBER_SUSPECT, 1])
        agent.state.apply(other.deltas_since({}))
        assert m.incarnation == 1
        m.tick()
        assert m.incarnation == 2
        assert m.status_of("node0") == MEMBER_ALIVE


class TestProtocolTick:
    def _suspect(self, m, target, rounds=10):
        for _ in range(rounds):
            m.tick()
            if m.status_of(target) == MEMBER_SUSPECT:
                return
        raise AssertionError(f"{target} never became suspect")

    def test_failed_probe_and_relays_mark_suspect(self):
        m, agent, client, clock = _mkmember(
            ok={"node2": False}, seed=7)
        self._suspect(m, "node2")
        # the direct ping failed, so a relay was consulted before the
        # suspicion was published (SWIM indirect probing)
        assert ("relay", "node1", "node2") in client.calls
        assert "node2" in m.live_ids(["node0", "node1", "node2"])

    def test_indirect_ack_keeps_target_alive(self):
        m, agent, client, clock = _mkmember(
            ok={"node2": False, ("via", "node1", "node2"): True}, seed=7)
        for _ in range(10):
            m.tick()
        assert m.status_of("node2") == MEMBER_ALIVE
        assert ("relay", "node1", "node2") in client.calls

    def test_suspect_expires_to_down_after_scaled_timeout(self):
        m, agent, client, clock = _mkmember(ok={"node2": False}, seed=7)
        self._suspect(m, "node2")
        m.tick()  # the expiry scan AFTER publication seeds the timer
        timeout = m.suspect_timeout_s(3)
        clock.advance(timeout / 2)
        m.tick()
        assert m.status_of("node2") == MEMBER_SUSPECT  # not yet
        clock.advance(timeout / 2 + 0.01)
        out = m.tick()
        assert "node2" in out["confirmed"]
        assert m.status_of("node2") == MEMBER_DOWN
        assert m.live_ids(["node0", "node1", "node2"]) == ["node0", "node1"]
        # confirmed-down targets stop being probe candidates
        calls_before = len(client.calls)
        m.tick()
        assert all(c != ("ping", "node2")
                   for c in client.calls[calls_before:])

    def test_recovered_probe_withdraws_suspicion(self):
        m, agent, client, clock = _mkmember(ok={"node2": False}, seed=7)
        self._suspect(m, "node2")
        client.ok["node2"] = True  # link back
        for _ in range(10):
            m.tick()
        # our own suspicion is the only record, so our alive re-assert
        # at the same incarnation cannot clear it (rank) — but positive
        # evidence does, because WE published the suspicion and bump to
        # alive replaces our own record
        m.evidence_alive("node2")
        # merged view: our origin now says alive; no other suspicion
        assert m.status_of("node2") in (MEMBER_ALIVE, MEMBER_SUSPECT)

    def test_suspect_timeout_scales_with_cluster_size(self):
        m, *_ = _mkmember(interval_ms=1000.0, suspect_mult=3.0)
        assert m.suspect_timeout_s(2) == pytest.approx(3.0)
        assert m.suspect_timeout_s(4) == pytest.approx(6.0)
        assert m.suspect_timeout_s(16) == pytest.approx(12.0)
        # tiny clusters clamp at the n=2 bound
        assert m.suspect_timeout_s(1) == pytest.approx(3.0)

    def test_probe_sequence_is_seeded_deterministic(self):
        seqs = []
        for _ in range(2):
            m, agent, client, clock = _mkmember(n=4, seed=11)
            for _ in range(8):
                m.tick()
            seqs.append([c for c in client.calls if c[0] == "ping"])
        assert seqs[0] == seqs[1]
        m, agent, client, clock = _mkmember(n=4, seed=12)
        for _ in range(8):
            m.tick()
        assert [c for c in client.calls if c[0] == "ping"] != seqs[0]

    def test_probe_payload_and_members_json(self):
        m, agent, client, clock = _mkmember(ok={"node1": False}, seed=7)
        self._suspect(m, "node1")
        m.tick()  # seeds the suspect timer (expiry scan precedes probe)
        p = m.probe()
        assert p["enabled"] is True
        assert p["suspect"] == 1 and p["down"] == 0
        assert p["recent_transitions"] >= 1
        j = m.members_json()
        assert j["node"] == "node0"
        assert j["members"]["node1"]["status"] == MEMBER_SUSPECT
        assert j["members"]["node1"]["suspect_for_s"] >= 0.0
        assert j["suspect_timeout_s"] == m.suspect_timeout_s(3)


class TestGossipDisCo:
    def test_liveness_comes_from_membership_not_seed(self):
        seed = InMemDisCo()
        for n in _mknodes(2):
            seed.register(n)
        stub = types.SimpleNamespace(evidence=[])
        stub.live_ids = lambda ids: [i for i in ids if i != "node1"]
        stub.evidence_down = lambda t: stub.evidence.append(("down", t))
        stub.evidence_alive = lambda t: stub.evidence.append(("up", t))
        d = GossipDisCo(seed, stub)
        assert [n.id for n in d.nodes()] == ["node0", "node1"]
        seed.down("node0")  # seed liveness is ignored...
        assert d.live_ids() == ["node0"]  # ...membership rules
        assert d.is_live("node0") and not d.is_live("node1")
        # mark_down/up (and the down/up aliases the harness uses) become
        # refutable evidence instead of authoritative state flips
        d.mark_down("node1")
        d.up("node1")
        assert stub.evidence == [("down", "node1"), ("up", "node1")]

    def test_register_delegates_to_seed(self):
        seed = InMemDisCo()
        d = GossipDisCo(seed, types.SimpleNamespace(
            live_ids=lambda ids: ids))
        d.register(Node(id="nX", uri=""))
        assert [n.id for n in seed.nodes()] == ["nX"]


class TestFaultPartition:
    def test_symmetric_cut_blocks_both_directions(self):
        plan = FaultPlan(seed=1).partition(["a", "b"], ["c"])
        with pytest.raises(InjectedFault):
            plan.on_request("c", source="a")
        with pytest.raises(InjectedFault):
            plan.on_request("b", source="c")
        # same side passes, and an anonymous client sees no links
        plan.on_request("b", source="a")
        plan.on_request("c")

    def test_asymmetric_cut_drops_one_direction(self):
        plan = FaultPlan(seed=1).partition(["a"], ["b"], symmetric=False)
        with pytest.raises(InjectedFault):
            plan.on_request("b", source="a")
        plan.on_request("a", source="b")  # reverse path delivers

    def test_op_scoped_cut_severs_only_that_rpc(self):
        plan = FaultPlan(seed=1).partition(["a"], ["b"], op="ping")
        with pytest.raises(InjectedFault):
            plan.on_request("b", source="a", op="ping")
        plan.on_request("b", source="a", op="gossip")
        plan.on_request("b", source="a", op="query")

    def test_heal_clears_links_but_keeps_node_rules(self):
        plan = FaultPlan(seed=1).partition(["a"], ["b"]).drop("z")
        with pytest.raises(InjectedFault):
            plan.on_request("b", source="a")
        plan.heal()
        plan.on_request("b", source="a")
        with pytest.raises(InjectedFault):
            plan.on_request("z", source="a")

    def test_partition_hits_are_recorded_events(self):
        plan = FaultPlan(seed=1).partition(["a"], ["b"])
        with pytest.raises(InjectedFault):
            plan.on_request("b", source="a")
        assert ("b", 0, "partition") in plan.events

    def test_count_window_arms_then_disarms(self):
        plan = FaultPlan(seed=1).partition(["a"], ["b"], count=2)
        for _ in range(2):
            with pytest.raises(InjectedFault):
                plan.on_request("b", source="a")
        plan.on_request("b", source="a")  # window exhausted


class _FlakyReplicator:
    """replicate_translate double: barrier-synchronized failure for the
    outbox race, then flippable to success for drain tests."""

    def __init__(self):
        self.fail = True
        self.barrier = None
        self.sent = []

    def replicate_translate(self, node, index, field, entries):
        if self.barrier is not None:
            self.barrier.wait(timeout=5.0)
        if self.fail:
            raise NodeDownError(f"replica {node.id} down")
        self.sent.append((node.id, index, field, list(entries)))


def _mktranslator(live=None):
    client = _FlakyReplicator()
    nodes = _mknodes(2)
    snap = ClusterSnapshot(nodes, replica_n=2)
    live = live if live is not None else {"node0", "node1"}
    tr = ClusterTranslator("node0", None, client, lambda: snap,
                           live_fn=lambda: set(live))
    return tr, client, nodes


class TestOutboxRace:
    def test_concurrent_failed_sends_lose_no_entries(self):
        # regression: pop/send/requeue used to race — two creates whose
        # pushes both failed could overwrite each other's requeue, and a
        # promoted replica then re-allocated the lost ids to other keys
        tr, client, nodes = _mktranslator()
        client.barrier = threading.Barrier(2)
        n1 = nodes[1]
        t1 = threading.Thread(
            target=tr._send_with_outbox, args=(n1, "i", None, [["a", 1]]))
        t2 = threading.Thread(
            target=tr._send_with_outbox, args=(n1, "i", None, [["b", 2]]))
        t1.start(); t2.start()
        t1.join(timeout=5.0); t2.join(timeout=5.0)
        assert tr.outbox_depth() == 2
        queued = sorted(tr._outbox[("node1", "i", None)])
        assert queued == [["a", 1], ["b", 2]]

    def test_failed_send_prepends_backlog_before_newer_entries(self):
        tr, client, nodes = _mktranslator()
        n1 = nodes[1]
        tr._send_with_outbox(n1, "i", None, [["a", 1]])
        tr._send_with_outbox(n1, "i", None, [["b", 2]])
        assert tr._outbox[("node1", "i", None)] == [["a", 1], ["b", 2]]

    def test_flush_drains_after_recovery(self):
        tr, client, nodes = _mktranslator()
        tr._send_with_outbox(nodes[1], "i", None, [["a", 1]])
        tr._send_with_outbox(nodes[1], "i", "f", [["r", 7]])
        assert tr.outbox_depth() == 2
        client.fail = False
        assert tr.flush_outbox() == 2
        assert tr.outbox_depth() == 0
        assert ("node1", "i", None, [["a", 1]]) in client.sent
        assert ("node1", "i", "f", [["r", 7]]) in client.sent
        assert tr.flush_outbox() == 0  # idempotent when empty

    def test_flush_keeps_entries_for_dead_replicas(self):
        live = {"node0"}
        tr, client, nodes = _mktranslator(live=live)
        tr._send_with_outbox(nodes[1], "i", None, [["a", 1]])
        client.fail = False
        assert tr.flush_outbox() == 0  # node1 not live: stays queued
        assert tr.outbox_depth() == 1
        live.add("node1")
        assert tr.flush_outbox() == 1
        assert tr.outbox_depth() == 0

    def test_gossip_publish_rides_every_push(self):
        tr, client, nodes = _mktranslator()
        published = []
        tr.gossip_publish = lambda *a: published.append(a)
        tr._push_entries("i", None, [("k1", 1)])
        tr._push_entries("i", "f", [("r1", 2)])
        assert published[0] == ("i", None, [["k1", 1]], 1)
        assert published[1] == ("i", "f", [["r1", 2]], 2)  # batch_no grows


# -- real 3-node clusters under partition plans ------------------------------


def _mkcluster(plan, n=3, replica_n=2, seed=9, **member_kw):
    clock = ManualClock()
    reg = MetricsRegistry()
    c = LocalCluster(
        n, replica_n=replica_n, fault_plan=plan,
        client_factory=lambda i: InternalClient(
            retries=0, backoff=0.001, fault_plan=plan))
    c.enable_gossip(seed=seed, clock=clock, registry=reg)
    c.enable_membership(seed=seed, clock=clock)
    return c, clock, reg


def _rounds(c, clock, n, only=None, advance=0.5):
    """n anti-entropy rounds (membership tick + outbox flush ride the
    round hooks), advancing the shared manual clock per round. ``only``
    restricts which nodes run (a truly dead node runs nothing)."""
    for _ in range(n):
        for i, node in enumerate(c.nodes):
            if only is not None and i not in only:
                continue
            node.gossip.run_round()
        clock.advance(advance)


def _statuses(c, i):
    return {nid: rec["status"]
            for nid, rec in c[i].membership.view().items()}


class TestClusterMembership:
    def test_all_alive_endpoint_and_wire_pings(self):
        c, clock, reg = _mkcluster(None, n=2)
        try:
            _rounds(c, clock, 2)
            with urllib.request.urlopen(
                    c[0].node.uri + "/internal/membership") as r:
                body = json.loads(r.read())
            assert body["enabled"] is True
            assert set(body["members"]) == {"node0", "node1"}
            assert all(v["status"] == MEMBER_ALIVE
                       for v in body["members"].values())
            # direct probe over the wire
            out = c[0].client.membership_ping(c[1].node, {"from": "node0"})
            assert out["ok"] is True and out["node"] == "node1"
            # ping-req relay: node1 probes node0 over ITS link and reports
            out = c[0].client.membership_ping(
                c[1].node, {"from": "node0",
                            "target": c[0].node.to_json()})
            assert out["ok"] is True and out["relay"] == "node1"
        finally:
            c.close()

    def test_membership_endpoint_reports_disabled_without_protocol(self):
        c = LocalCluster(1)
        try:
            with urllib.request.urlopen(
                    c[0].node.uri + "/internal/membership") as r:
                body = json.loads(r.read())
            assert body["enabled"] is False
            assert body["live"] == ["node0"]
        finally:
            c.close()

    def test_disable_membership_restores_seed_plumbing(self):
        c, clock, reg = _mkcluster(None, n=2)
        try:
            node = c[0]
            assert isinstance(node.disco, GossipDisCo)
            seed_disco = node.disco.seed
            agent = node.gossip
            node.disable_membership()
            assert node.membership is None
            assert node.disco is seed_disco
            assert not isinstance(node.disco, GossipDisCo)
            assert agent.round_hooks == []
            assert node.executor.translator.gossip_publish is None
            # gossip itself survives (membership rode it, not vice versa)
            assert node.gossip is agent
        finally:
            c.close()


class TestPartitionChaos:
    def _fill(self, target, index="mi"):
        target.create_index(index)
        target.create_field(index, "f")
        for s in range(4):
            for bit in (1, 2):
                target.query(index, f"Set({s * SHARD_WIDTH + bit}, f={s})")

    CHECKS = ["Count(Row(f=0))", "Row(f=1)", "Row(f=3)",
              "Count(Union(Row(f=0), Row(f=2)))"]

    def test_clean_split_confirm_heal_rejoin_bit_identical(self):
        plan = FaultPlan(seed=3)
        c, clock, reg = _mkcluster(plan)
        oracle = API()
        try:
            co = c.coordinator
            self._fill(co)
            self._fill(oracle)
            want = [oracle.query("mi", q) for q in self.CHECKS]
            _rounds(c, clock, 3)
            assert all(s == MEMBER_ALIVE for s in _statuses(c, 0).values())

            plan.partition(["node0", "node1"], ["node2"])
            # node2 is fully cut: its own rounds fail outward, the
            # majority's probes fail toward it. Run well past the
            # suspect timeout (0.5s x 3.0 x log2(3) ~ 2.4s at 0.5s/round).
            _rounds(c, clock, 14, only=(0, 1))
            assert _statuses(c, 0)["node2"] == MEMBER_DOWN
            assert _statuses(c, 1)["node2"] == MEMBER_DOWN
            assert set(c[0].disco.live_ids()) == {"node0", "node1"}
            # majority keeps serving: every shard has a replica on the
            # majority side (replica_n=2), reads fail over off node2
            for q, w in zip(self.CHECKS, want):
                assert co.query("mi", q) == w

            plan.heal()
            _rounds(c, clock, 12)
            # node2 saw its own confirmation, refuted with an
            # incarnation bump, and rejoined; the majority's down
            # records are outranked by alive@inc+1
            assert c[2].membership.incarnation > 1
            for i in range(3):
                assert all(s == MEMBER_ALIVE
                           for s in _statuses(c, i).values()), i
            assert set(c[0].disco.live_ids()) == \
                {"node0", "node1", "node2"}

            # post-heal writes land everywhere; results bit-identical
            # to the no-fault oracle from every coordinator
            co.query("mi", f"Set({2 * SHARD_WIDTH + 9}, f=7)")
            oracle.query("mi", f"Set({2 * SHARD_WIDTH + 9}, f=7)")
            checks = self.CHECKS + ["Row(f=7)"]
            want = [oracle.query("mi", q) for q in checks]
            for node in c.nodes:
                for q, w in zip(checks, want):
                    assert node.query("mi", q) == w, (node.node.id, q)
        finally:
            c.close()

    def test_asymmetric_ping_cut_refutes_and_flaps(self):
        # only PROBES toward node2 drop; gossip and queries deliver, so
        # every suspicion reaches node2, which refutes with an
        # incarnation bump — the cluster flaps instead of wrongly
        # confirming a live node down
        plan = FaultPlan(seed=3)
        c, clock, reg = _mkcluster(plan)
        try:
            _rounds(c, clock, 2)
            inc0 = c[2].membership.incarnation
            plan.partition(["node0", "node1"], ["node2"],
                           symmetric=False, op="ping")
            # short rounds: suspicions never live long enough to confirm
            _rounds(c, clock, 10, advance=0.2)
            assert c[2].membership.incarnation > inc0
            assert _statuses(c, 0)["node2"] != MEMBER_DOWN
            assert _statuses(c, 1)["node2"] != MEMBER_DOWN
            # node2 stays in everyone's routing set throughout
            assert "node2" in c[0].disco.live_ids()
            # the oscillation is visible to the flap accounting
            flaps = sum(node.membership.recent_transitions()
                        for node in c.nodes)
            assert flaps >= 2
        finally:
            c.close()

    def test_coordinator_drop_broadcast_and_translate_converge(self):
        plan = FaultPlan(seed=5)
        c, clock, reg = _mkcluster(plan)
        try:
            _rounds(c, clock, 2)
            plan.partition(["node0"], ["node1", "node2"])

            # schema created while the coordinator is unreachable: the
            # direct push to node0 fails, GossipBroadcaster tolerates it
            # and records an idempotent control entry
            c[1].create_index("pk", {"keys": True})
            c[1].create_field("pk", "color")
            assert "pk" in c[2].holder.indexes  # direct push delivered
            assert "pk" not in c[0].holder.indexes  # cut off

            # keyed translation while node0 is cut: pick keys whose
            # partition primary is reachable and whose replica set
            # includes node0, so the replica push queues in the outbox
            # and the entries also ride the gossip plane
            snap = ClusterSnapshot(c[1].disco.nodes(), replica_n=2)
            keys = []
            for i in range(64):
                owners = [n.id for n in snap.key_nodes("pk", f"k{i}")]
                if owners[0] != "node0" and "node0" in owners[1:]:
                    keys.append(f"k{i}")
                if len(keys) == 3:
                    break
            assert len(keys) == 3
            ids = c[1].executor.translator.index_keys("pk", keys, True)
            assert sorted(ids) == sorted(keys)

            plan.heal()
            _rounds(c, clock, 8)
            # node0 converged on the schema via the gossiped control
            # entries — nobody re-broadcast anything
            assert "pk" in c[0].holder.indexes
            # and holds the same key->id map, via outbox drain + the
            # gossiped translate batches
            local = c[0].holder.index("pk").translate.find_keys(keys)
            assert local == ids
            for node in c.nodes:
                assert node.executor.translator.outbox_depth() == 0
        finally:
            c.close()

    def test_paused_node_is_suspected_but_refutes_while_sending(self):
        # harness pause kills only the listener: the paused node still
        # gossips OUTBOUND, hears the suspicion in reply envelopes, and
        # keeps refuting — SWIM never confirms a node that can prove
        # liveness. Only a truly silent node (no rounds at all) confirms.
        c, clock, reg = _mkcluster(None)
        try:
            _rounds(c, clock, 2)
            c.pause(2)
            _rounds(c, clock, 10)
            assert c[2].membership.incarnation > 1  # kept refuting
            assert _statuses(c, 0)["node2"] != MEMBER_DOWN
            # now truly silent: only the majority runs rounds
            _rounds(c, clock, 14, only=(0, 1))
            assert _statuses(c, 0)["node2"] == MEMBER_DOWN
            assert set(c[0].disco.live_ids()) == {"node0", "node1"}
            c.unpause(2)
            _rounds(c, clock, 10)
            assert all(s == MEMBER_ALIVE
                       for s in _statuses(c, 0).values())
        finally:
            c.close()
