"""Graceful-degradation (brownout) control plane (sched/degrade.py).

Unit-level: the hysteresis ladder (escalation jumps, one-rung recovery,
hold streaks, dwell), shed ordering (batch from SHED_BATCH, interactive
only at SATURATED), honest Retry-After propagation, and deadline
tightening. Integration-level: scheduler admission sheds, brownout
stale-serving through the result cache with the ``stale=true`` response
tag, the bulk-import ingress shed, and the PILOSA_TPU_DEGRADE=0
zero-cost-off contract. bench.py config 22 drives the same ladder
against a live 3-node cluster under open-loop overload.
"""

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.errors import AdmissionError
from pilosa_tpu.obs.metrics import (METRIC_DEGRADE_STATE,
                                    METRIC_DEGRADE_TRANSITIONS,
                                    MetricsRegistry)
from pilosa_tpu.sched.degrade import (BROWNOUT, NORMAL, SATURATED,
                                      SHED_BATCH, DegradeController)


def sample(t, queue_frac=0.0, burn=0.0, rates=None):
    """One synthetic timeline sample shaped like HealthPlane's."""
    mq = 100.0
    return {
        "t": t,
        "probes": {
            "scheduler": {"max_queue": mq,
                          "queue_depth": queue_frac * mq,
                          "inflight_admits": 0},
            "slo": {"max_fast_burn": burn},
        },
        "rates": dict(rates or {}),
    }


def controller(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("min_dwell_s", 0.0)
    kw.setdefault("up_hold", 1)
    kw.setdefault("down_hold", 1)
    return DegradeController(**kw)


class TestLadderHysteresis:
    def test_escalation_jumps_recovery_steps(self):
        deg = controller()
        deg.observe(sample(0.0, queue_frac=0.99))
        assert deg.level == SATURATED  # escalation may jump rungs
        levels = []
        for i in range(1, 5):
            deg.observe(sample(float(i), queue_frac=0.0))
            levels.append(deg.level)
        # recovery is deliberate: one rung per qualifying sample
        assert levels == [BROWNOUT, SHED_BATCH, NORMAL, NORMAL]

    def test_up_hold_requires_consecutive_samples(self):
        deg = controller(up_hold=2)
        deg.observe(sample(0.0, queue_frac=0.99))
        assert deg.level == NORMAL  # one hot sample is not enough
        deg.observe(sample(0.1, queue_frac=0.0))  # streak broken
        deg.observe(sample(0.2, queue_frac=0.99))
        assert deg.level == NORMAL
        deg.observe(sample(0.3, queue_frac=0.99))  # second consecutive
        assert deg.level == SATURATED

    def test_down_hold_and_exit_band(self):
        deg = controller(queue_shed=0.5, exit_ratio=0.7, down_hold=2)
        deg.observe(sample(0.0, queue_frac=0.6))
        assert deg.level == SHED_BATCH
        # inside the hysteresis band (exit edge 0.35 <= q < 0.5):
        # neither escalation nor recovery, and streaks reset
        for i in range(1, 6):
            deg.observe(sample(float(i), queue_frac=0.4))
            assert deg.level == SHED_BATCH
        deg.observe(sample(6.0, queue_frac=0.1))
        assert deg.level == SHED_BATCH  # down_hold=2: first sample holds
        deg.observe(sample(7.0, queue_frac=0.1))
        assert deg.level == NORMAL

    def test_min_dwell_blocks_flapping(self):
        deg = controller(min_dwell_s=1.0, down_hold=1)
        deg.observe(sample(0.0, queue_frac=0.99))
        assert deg.level == SATURATED
        deg.observe(sample(0.5, queue_frac=0.0))  # too soon to move
        assert deg.level == SATURATED
        deg.observe(sample(1.5, queue_frac=0.0))
        assert deg.level == BROWNOUT

    def test_burn_and_aux_signals_drive_ladder(self):
        deg = controller(burn_shed=2.0, burn_brownout=6.0,
                         burn_saturate=14.0)
        deg.observe(sample(0.0, burn=7.0))
        assert deg.level == BROWNOUT
        deg.reset()
        # deadline-miss rate is a BROWNOUT signal, evictions a
        # SHED_BATCH signal; both arrive via the counter-delta map
        deg2 = controller(miss_rate_brownout=1.0)
        deg2.observe(
            sample(0.0, rates={"sched_deadline_missed_total": 2.0}))
        assert deg2.level == BROWNOUT
        deg3 = controller(eviction_rate_shed=5.0)
        deg3.observe(
            sample(0.0, rates={"device_budget_evictions_total": 9.0}))
        assert deg3.level == SHED_BATCH

    def test_transitions_are_metered_and_recorded(self):
        reg = MetricsRegistry()
        deg = controller(registry=reg)

        class FakeFlight:
            def __init__(self):
                self.events = []
                self.triggers = []

            def record_event(self, kind, **info):
                self.events.append((kind, info))

            def trigger(self, name, reason, sample=None):
                self.triggers.append((name, reason))

        deg.flight = fl = FakeFlight()
        deg.observe(sample(0.0, queue_frac=0.99))
        deg.observe(sample(1.0))
        assert deg.probe()["transitions"] == 2
        assert [k for k, _ in fl.events] == ["degrade_transition"] * 2
        assert fl.triggers and fl.triggers[0][0] == "degrade_escalation"
        text = reg.prometheus_text()
        assert METRIC_DEGRADE_STATE in text
        assert METRIC_DEGRADE_TRANSITIONS in text


class TestShedContract:
    def test_shed_order_batch_before_interactive(self):
        deg = controller()
        assert deg.shed_reason("batch") is None
        deg._level = SHED_BATCH
        assert deg.shed_reason("batch") == "degrade_shed_batch"
        assert deg.shed_reason("interactive") is None
        deg._level = BROWNOUT
        assert deg.shed_reason("interactive") is None
        deg._level = SATURATED
        assert deg.shed_reason("batch") == "degrade_shed_batch"
        assert deg.shed_reason("interactive") == "degrade_saturated"

    def test_shed_carries_live_retry_after(self):
        deg = controller(retry_after_s=2.5)
        deg._level = SATURATED
        err = deg.shed("interactive")
        assert isinstance(err, AdmissionError)
        assert err.retry_after_s == 2.5  # static default until wired
        deg.retry_after_fn = lambda: 0.75
        assert deg.shed("batch").retry_after_s == 0.75
        assert deg.shed("batch", retry_after_s=0.2).retry_after_s == 0.2

    def test_tighten_deadline_only_at_brownout(self):
        deg = controller(deadline_factor=0.5, brownout_deadline_ms=250.0)
        assert deg.tighten_deadline(1.0) == 1.0
        deg._level = BROWNOUT
        assert deg.tighten_deadline(1.0) == 0.5
        assert deg.tighten_deadline(0.0) == 0.25  # imposed default


class TestSchedulerIntegration:
    @pytest.fixture
    def api(self):
        a = API()
        a.create_index("i")
        a.create_field("i", "f")
        a.import_bits("i", "f", rows=[1, 1, 2], cols=[1, 2, 3])
        a.enable_scheduler()
        yield a
        a.disable_scheduler()

    def test_admission_sheds_in_ladder_order(self, api):
        deg = api.enable_degrade(min_dwell_s=0.0)
        deg._level = SHED_BATCH
        with pytest.raises(AdmissionError) as ei:
            with api.scheduler.admit(priority="batch"):
                pass
        assert ei.value.retry_after_s > 0
        assert "batch" in str(ei.value)
        # interactive flows at SHED_BATCH, sheds only at SATURATED
        assert api.query_json("i", "Count(Row(f=1))")["results"] == [2]
        deg._level = SATURATED
        with pytest.raises(AdmissionError):
            api.query_json("i", "Count(Row(f=1))")

    def test_import_ingress_shed_helper(self, api):
        deg = api.enable_degrade()
        api._degrade_shed_batch()  # NORMAL: no-op
        deg._level = SHED_BATCH
        with pytest.raises(AdmissionError):
            api._degrade_shed_batch()
        # direct import_bits is NOT shed: SQL DML, WAL replay, and
        # fan-out legs must never be torn mid-statement
        assert api.import_bits("i", "f", rows=[3], cols=[9]) == 1

    def test_zero_cost_off(self, api):
        api.disable_degrade()  # under the PILOSA_TPU_DEGRADE=1 lane
        assert api.degrade is None
        reg = api.scheduler.registry

        def degrade_lines():
            # the registry is process-global: other tests may have moved
            # degrade metrics, so zero-cost means NO MOVEMENT, not absence
            return [line for line in reg.prometheus_text().splitlines()
                    if "degrade_" in line]

        before = degrade_lines()
        with api.scheduler.admit(priority="batch"):
            pass
        assert api.query_json("i", "Count(Row(f=2))")["results"] == [1]
        assert degrade_lines() == before

    def test_env_auto_enable(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_DEGRADE", "1")
        a = API()
        try:
            assert a.degrade is not None
            assert a.degrade.level == NORMAL
        finally:
            a.disable_scheduler()


class TestBrownoutStaleServing:
    def test_stale_serve_is_tagged_and_recovers(self):
        api = API()
        try:
            api.create_index("i")
            api.create_field("i", "f")
            api.import_bits("i", "f", rows=[1, 1], cols=[1, 2])
            api.enable_cache()
            deg = api.enable_degrade()
            q = "Count(Row(f=1))"
            assert api.query_json("i", q) == {"results": [2]}
            # the write moves the version fingerprint: the cached entry
            # is now stale-by-version, not expired
            api.import_bits("i", "f", rows=[1], cols=[3])
            fresh = api.query_json("i", q)
            assert fresh == {"results": [3]}
            api.import_bits("i", "f", rows=[1], cols=[4])
            deg._level = BROWNOUT
            browned = api.query_json("i", q)
            assert browned["results"] == [3]  # previous answer
            assert browned["stale"] is True
            assert api.cache.stats()["stale_serves"] == 1
            # recovery: fresh execution again, no stale tag
            deg.reset()
            recovered = api.query_json("i", q)
            assert recovered == {"results": [4]}
        finally:
            api.disable_cache()

    def test_stale_disabled_for_remote_legs(self):
        from pilosa_tpu.cache.result_cache import ResultCache

        cache = ResultCache(registry=MetricsRegistry())
        deg = controller()
        deg._level = BROWNOUT
        cache.degrade = deg
        key = ("q", "i", "fp1")
        cache.run(key, lambda: [1])
        moved = ("q", "i", "fp2")
        # client-facing leg: stale predecessor served and flagged
        hit, value = cache.lookup(moved)
        assert (hit, value) == (True, [1])
        assert cache.take_stale_flag() is True
        # remote-serving leg: allow_stale=False never serves stale
        hit, _ = cache.lookup(moved, allow_stale=False)
        assert hit is False
        assert cache.take_stale_flag() is False
