"""Bit-identity battery: every Pallas L0 kernel vs its classic oracle.

The Pallas plane's contract is *bit-identical or bust* — these tests
force the kernels on in interpret mode (``PILOSA_TPU_PALLAS=1`` on the
CPU backend runs the exact kernel bodies under the Pallas interpreter)
and compare against the classic XLA/numpy paths across the edge shapes
that historically break tiled kernels: empty filters, all-set planes, a
single word, row counts that are not a multiple of any tile, negative
BSI values, and BETWEEN ranges straddling zero. The same calls run once
more with the kill switch thrown to pin the zero-dispatch guarantee.
"""

import numpy as np
import pytest

from pilosa_tpu.obs import metrics as M
from pilosa_tpu.ops import bitmap as B
from pilosa_tpu.ops import bsi as S
from pilosa_tpu.ops import groupby as G
from pilosa_tpu.ops import pallas_util as PU
from pilosa_tpu.ops import scatter as SC
from pilosa_tpu.ops import topk as T

WORDS = 1 << 9
NBITS = WORDS * 32


@pytest.fixture(autouse=True)
def _clean_strikes():
    """Strike counters must not leak between tests (a kernel pinned off
    by an earlier failure would silently skip the parity assertion)."""
    PU.reset_failures()
    yield
    PU.reset_failures()


@pytest.fixture
def forced(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    monkeypatch.delenv("PILOSA_TPU_NO_PALLAS", raising=False)


@pytest.fixture
def killed(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")


def rand_planes(rng, rows, words=WORDS):
    return rng.integers(0, 1 << 32, size=(rows, words), dtype=np.uint32)


def dispatch_count(kernel):
    return M.REGISTRY.value(M.METRIC_OPS_PALLAS_DISPATCH, kernel=kernel)


# ---------------------------------------------------------------------------
# pair_counts (GroupBy)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("r1,r2,w", [
    (1, 1, 1),      # single word
    (3, 5, 7),      # nothing aligned
    (37, 37, 512),  # rows not a multiple of any tile
    (8, 256, 512),  # exactly tile-aligned
])
def test_pair_counts_parity(rng, forced, r1, r2, w):
    a, b = rand_planes(rng, r1, w), rand_planes(rng, r2, w)
    before = dispatch_count("pair_counts")
    got = np.asarray(G.pair_counts(a, b))
    assert dispatch_count("pair_counts") == before + 1
    want = np.asarray(G._pair_counts_xla(a, b))
    np.testing.assert_array_equal(got, want)


def test_pair_counts_all_set_and_empty(rng, forced):
    ones = np.full((4, WORDS), 0xFFFFFFFF, dtype=np.uint32)
    zeros = np.zeros((4, WORDS), dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(G.pair_counts(ones, ones)),
        np.full((4, 4), NBITS, dtype=np.int32))
    np.testing.assert_array_equal(
        np.asarray(G.pair_counts(ones, zeros)), np.zeros((4, 4), np.int32))


# ---------------------------------------------------------------------------
# BSI sum / plane popcounts
# ---------------------------------------------------------------------------


def encode(rng, n=2000, lo=-5000, hi=5000):
    cols = np.unique(rng.integers(0, NBITS, size=n))
    vals = rng.integers(lo, hi, size=cols.size)
    depth = max(S.bits_needed(int(vals.min())),
                S.bits_needed(int(vals.max())))
    return cols, vals, S.encode_values(cols, vals, depth, WORDS)


def test_bsi_sum_parity_negative_values(rng, forced):
    cols, vals, planes = encode(rng)
    filt = np.asarray(planes[S.EXISTS])
    before = dispatch_count("bsi_sum")
    total, count = S.bsi_sum(planes, planes[S.EXISTS])
    assert dispatch_count("bsi_sum") == before + 1
    assert (total, count) == (int(vals.sum()), cols.size)
    # plane popcounts against the classic reduction, element by element
    got = S.bsi_plane_popcounts(planes, planes[S.EXISTS])
    want = S._plane_popcounts_xla(planes, planes[S.EXISTS])
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    del filt


def test_bsi_sum_empty_filter(rng, forced):
    _, _, planes = encode(rng)
    total, count = S.bsi_sum(planes, B.device_zeros(WORDS))
    assert (total, count) == (0, 0)


# ---------------------------------------------------------------------------
# BSI compare
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("op", [S.EQ, S.NE, S.LT, S.LE, S.GT, S.GE])
@pytest.mark.parametrize("c", [-6000, -1, 0, 42, 6000])
def test_bsi_compare_parity(rng, forced, monkeypatch, op, c):
    cols, vals, planes = encode(rng)
    before = dispatch_count("bsi_compare")
    got = np.asarray(S.bsi_compare(planes, op, c))
    assert dispatch_count("bsi_compare") == before + 1
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    want = np.asarray(S.bsi_compare(planes, op, c))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("a,b", [
    (-100, 100),     # straddles zero
    (0, 0), (-5000, 5000), (40, 30), (-5000, -4000), (-6000, 6000),
])
def test_bsi_between_parity(rng, forced, monkeypatch, a, b):
    cols, vals, planes = encode(rng)
    got = np.asarray(S.bsi_compare(planes, S.BETWEEN, a, b))
    expect = set(int(x) for x in cols[(vals >= a) & (vals <= b)])
    assert set(int(x) for x in B.plane_to_bits(got)) == expect
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    want = np.asarray(S.bsi_compare(planes, S.BETWEEN, a, b))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# TopN row counts / ranking
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rows", [1, 37, 64])
def test_row_counts_parity(rng, forced, rows):
    planes = rand_planes(rng, rows)
    filt = rand_planes(rng, 1)[0]
    for f in (None, filt):
        got = np.asarray(T.row_counts(planes, f))
        want = np.asarray(B.row_counts(planes, f))
        np.testing.assert_array_equal(got, want)


def test_top_rows_parity(rng, forced):
    planes = rand_planes(rng, 37)
    filt = rand_planes(rng, 1)[0]
    for f in (None, filt):
        gc, gi = T.top_rows(planes, 5, f)
        wc, wi = T._topk_kernel(planes, f if f is not None else None, 5)
        np.testing.assert_array_equal(np.asarray(gc), np.asarray(wc))
        # indices may tie-break differently only among equal counts
        counts = np.asarray(B.row_counts(planes, f))
        np.testing.assert_array_equal(counts[np.asarray(gi)],
                                      np.asarray(gc))
        del wi


# ---------------------------------------------------------------------------
# Ingest scatter
# ---------------------------------------------------------------------------


def test_sort_updates_collapses_duplicates():
    slots = np.array([0, 0, 1, 0], dtype=np.int64)
    cols = np.array([0, 0, 33, 31], dtype=np.int64)
    addr, masks = SC.sort_updates(slots, cols, words=4)
    np.testing.assert_array_equal(addr, [0, 5])
    np.testing.assert_array_equal(masks, [0x80000001, 0x2])
    a0, m0 = SC.sort_updates([], [], words=4)
    assert a0.size == 0 and m0.size == 0


def test_scatter_merge_parity(rng, forced):
    import jax.numpy as jnp

    flat = rng.integers(0, 1 << 32, size=1024, dtype=np.uint32)
    addr, masks = SC.sort_updates(
        np.zeros(300, dtype=np.int64),
        rng.integers(0, 1024 * 32, size=300), words=1024)
    dev = jnp.asarray(flat)
    ai = jnp.asarray(addr.astype(np.int32))
    mi = jnp.asarray(masks)
    gm, gc = SC._scatter_merge_pallas(dev, ai, mi, True)
    wm, wc = SC._scatter_merge_xla(dev, ai, mi)
    np.testing.assert_array_equal(np.asarray(gm), np.asarray(wm))
    assert int(gc) == int(wc)


def test_set_many_device_vs_classic(rng, forced, monkeypatch):
    from pilosa_tpu.core.fragment import SetFragment

    rows = rng.integers(0, 8, size=500)
    cols = rng.integers(0, NBITS, size=500)
    dev = SetFragment(0, words=WORDS)
    before = dispatch_count("ingest_scatter")
    ch_dev = dev.set_many(rows, cols)
    assert dispatch_count("ingest_scatter") == before + 1
    assert dev.set_many(rows, cols) == 0  # idempotent re-apply

    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    classic = SetFragment(0, words=WORDS)
    ch_cl = classic.set_many(rows, cols)
    assert ch_dev == ch_cl
    assert sorted(dev.existing_rows()) == sorted(classic.existing_rows())
    for r in dev.existing_rows():
        np.testing.assert_array_equal(dev.row_plane(r),
                                      classic.row_plane(r))


# ---------------------------------------------------------------------------
# Tape-count terminal (resident program popcount reduce)
# ---------------------------------------------------------------------------


def test_tape_count_terminal_parity(rng, forced):
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.parallel import mesh

    mesh.set_engine_mesh(mesh.analytics_mesh([jax.devices()[0]]))
    try:
        total_words = 1024
        leaves = [jnp.asarray(rand_planes(rng, 1, total_words)[0])
                  for _ in range(2)]
        tape = (("and", 0, 1),)
        fn = mesh.compile_tape_count(tape, False, total_words)
        assert getattr(fn, "pallas_terminal", False)
        got = int(fn(*leaves))
        want = int(np.sum([bin(int(w)).count("1") for w in
                           np.asarray(leaves[0] & leaves[1])]))
        assert got == want
    finally:
        mesh.set_engine_mesh(None)


def test_plane_count_pallas_2d(rng, forced):
    import jax.numpy as jnp

    x = rand_planes(rng, 4, 512)
    got = int(B.plane_count_pallas_traced(jnp.asarray(x), True))
    assert got == int(np.unpackbits(x.view(np.uint8)).sum())


# ---------------------------------------------------------------------------
# Kill switch + metrics exposition
# ---------------------------------------------------------------------------


def _fallback_total():
    return sum(v for key, v in M.REGISTRY.snapshot()["counters"].items()
               if key.startswith(M.METRIC_OPS_PALLAS_FALLBACK))


def test_kill_switch_zero_dispatch_zero_overhead(rng, killed):
    a, b = rand_planes(rng, 4), rand_planes(rng, 4)
    snap_d = M.REGISTRY.value(M.METRIC_OPS_PALLAS_DISPATCH,
                              kernel="pair_counts")
    snap_f = _fallback_total()
    np.testing.assert_array_equal(np.asarray(G.pair_counts(a, b)),
                                  np.asarray(G._pair_counts_xla(a, b)))
    S.bsi_compare(encode(np.random.default_rng(7))[2], S.GT, 0)
    assert M.REGISTRY.value(M.METRIC_OPS_PALLAS_DISPATCH,
                            kernel="pair_counts") == snap_d
    # the switch must not even tick the fallback counter
    assert _fallback_total() == snap_f


def test_legacy_no_pallas_env_still_disables(rng, monkeypatch):
    monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)
    monkeypatch.setenv("PILOSA_TPU_NO_PALLAS", "1")
    assert PU.disabled()
    assert PU.why_not("pair_counts") == "disabled"


def test_metrics_exposition(rng, forced):
    a, b = rand_planes(rng, 2), rand_planes(rng, 2)
    G.pair_counts(a, b)
    PU.fallback("pair_counts", "shape")
    text = M.REGISTRY.prometheus_text()
    assert 'ops_pallas_dispatch_total{kernel="pair_counts"}' in text
    assert 'ops_pallas_fallback_total{' in text
    assert 'why="shape"' in text


def test_failure_strikeout(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    assert PU.why_not("demo_kernel") is None
    PU.failed("demo_kernel", RuntimeError("boom"))
    assert PU.why_not("demo_kernel") is None  # one strike: still on
    for _ in range(PU.MAX_FAILURES):
        PU.failed("demo_kernel", RuntimeError("boom"))
    assert PU.why_not("demo_kernel") == "failures"
    PU.reset_failures()
    assert PU.why_not("demo_kernel") is None


def test_mode_token_tracks_kill_switch(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    on = PU.mode_token()
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    off = PU.mode_token()
    assert on != off and off == "classic"
